"""Sharded train-state init and train-step builder.

The TPU-native core loop: one jitted function computes grads, applies the
optimizer, and XLA inserts every collective (psum over ``dp``/``fsdp`` for
grads, all-gathers for TP activations) from the sharding constraints — the
replacement for the reference's wrapper stack of DDP/FSDP/TP modules
(atorch auto/model_context.py apply-wrapper pipeline).

Gradient accumulation is a ``lax.scan`` over microbatches, which is also the
elasticity lever: the ElasticTrainer keeps the *global* batch constant when
the world shrinks by raising ``grad_accum`` (reference:
trainer/torch/elastic/trainer.py:48).
"""

import functools
import logging
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common import jax_compat
from dlrover_tpu.models import decoder
from dlrover_tpu.models.config import ModelConfig
from dlrover_tpu.observability import sentinels as snt
from dlrover_tpu.parallel import sharding as shd

logger = logging.getLogger(__name__)

TrainState = Dict[str, Any]  # {"params", "opt_state", "step"}

# Host-offloaded optimizer state (reference parity: atorch's CPU-offload
# Adam, SURVEY §2.3 Optimizers). TPU-native: the moments live in
# pinned_host memory via sharding memory kinds — XLA streams them over
# the host DMA around the update, freeing ~2x param bytes of HBM. No
# custom op and no separate optimizer implementation needed. (On the CPU
# backend the Host space aliases device memory — a harmless no-op that
# keeps the same code path testable on the virtual mesh.)
_HOST = jax_compat.HOST_MEMORY
_DEVICE = jax_compat.DEVICE_MEMORY


def _to_memory_kind(tree, kind):
    return jax.tree.map(lambda x: jax.device_put(x, kind), tree)


def batch_sharding(mesh: Mesh, rules=None) -> NamedSharding:
    """Sharding for [B, S] token batches."""
    rules = dict(shd.DEFAULT_RULES, **(rules or {}))
    return NamedSharding(
        mesh, shd.logical_to_mesh_axes(("batch", "seq"), rules)
    )


def _is_quantized(x) -> bool:
    from dlrover_tpu.ops.quant import QuantizedArray

    return isinstance(x, QuantizedArray)


def _map_param_subtrees(
    opt_tree, params, param_shardings, param_leaf_fn, other_fn
):
    """Map over an optimizer-state tree, matching param-STRUCTURED
    subtrees (Adam mu/nu etc.) by tree structure, not leaf shape —
    same-shape params can carry transposed shardings, and a shape-keyed
    lookup would pin their moments to the wrong one.

    ``param_leaf_fn(leaf, param_sharding)`` is applied leaf-wise inside
    matched subtrees (QuantizedArray nodes treated as leaves);
    ``other_fn(subtree)`` covers everything else (step counters, …).
    The ONE structure-matching rule both the init constraints and the
    host-offload shardings build on."""
    pdef = jax.tree.structure(params)

    def is_param_tree(x):
        try:
            return (
                jax.tree.structure(x, is_leaf=_is_quantized) == pdef
            )
        except Exception:  # noqa: BLE001
            return False

    def con(sub):
        if is_param_tree(sub):
            return jax.tree.map(
                param_leaf_fn, sub, param_shardings,
                is_leaf=_is_quantized,
            )
        return other_fn(sub)

    return jax.tree.map(con, opt_tree, is_leaf=is_param_tree)


def _opt_state_host_shardings(opt_shape, params, param_shardings, mesh):
    """Per-leaf pinned_host NamedShardings for an optimizer-state tree:
    param-shaped subtrees inherit the param shardings (host kind), the
    rest (step counters, quantized-array innards) replicate on host."""
    rep = NamedSharding(mesh, P(), memory_kind="pinned_host")
    return _map_param_subtrees(
        opt_shape,
        params,
        param_shardings,
        param_leaf_fn=lambda leaf, s: jax.tree.map(lambda _: rep, leaf)
        if _is_quantized(leaf)
        else s.with_memory_kind("pinned_host"),
        other_fn=lambda sub: jax.tree.map(lambda _: rep, sub),
    )


# ---------------------------------------------------------------------------
# Weight-update sharding (ZeRO-1): gate resolution + flat optimizer state
# ---------------------------------------------------------------------------


def _flat_abs(plan: shd.PackPlan):
    return {
        "flat": jax.ShapeDtypeStruct(
            (plan.n_buckets, plan.bucket_elems), jnp.float32
        )
    }


def _effective_flat_optimizer(
    optimizer: optax.GradientTransformation, plan: shd.PackPlan
) -> optax.GradientTransformation:
    """The transformation actually run on the flat bucketed view.

    Most optimizers are elementwise over the view and run as-is. An
    optimizer whose init fn carries a ``_flat_factory`` attribute
    (optimizer.py's factored path) instead supplies a plan-aware flat
    equivalent: the factory knows the pack layout, so it can rebuild
    per-leaf views out of the flat stream and keep non-elementwise
    state (Adafactor row/col accumulators) per leaf rather than
    mis-factoring the bucket matrix.
    """
    factory = getattr(optimizer.init, "_flat_factory", None)
    return factory(plan) if factory is not None else optimizer


def _probe_flat_optimizer(
    optimizer: optax.GradientTransformation, plan: shd.PackPlan
) -> Optional[str]:
    """None when the optimizer's state is elementwise over the flat
    bucketed param view (so dp-sharding the flat axis shards the state)
    or the optimizer supplies a plan-aware flat equivalent
    (``_flat_factory``), else the reason it is not."""
    eff = _effective_flat_optimizer(optimizer, plan)
    try:
        opt_abs = jax.eval_shape(eff.init, _flat_abs(plan))
    except Exception as e:  # noqa: BLE001
        return f"optimizer.init rejected the flat param view: {e}"
    flat_shape = (plan.n_buckets, plan.bucket_elems)
    for leaf in jax.tree.leaves(opt_abs, is_leaf=_is_quantized):
        if _is_quantized(leaf):
            return "low-bit optimizer state (compiler-chosen shardings)"
        if eff is not optimizer:
            # plan-aware flat optimizer: per-leaf factored state is
            # expected; only (n_buckets, bucket_elems)-shaped leaves
            # get dp-sharded (_flat_opt_sharding), the rest replicate
            continue
        if tuple(leaf.shape) not in ((), flat_shape):
            return (
                f"optimizer state leaf of shape {tuple(leaf.shape)} is "
                "not elementwise over the flat view (factored states "
                "would mis-factor the bucket matrix)"
            )
    return None


# fallback reasons already logged, keyed (reason, config name): the
# resolver runs on every trace (builder init, abstract/init state, AOT
# prewarm), and re-warning the same fallback each time buries real
# warnings. The chosen reason also rides the bench/MULTICHIP records
# (TrainStepBuilder.update_sharding_reason), which is where a fallback
# should be noticed.
_LOGGED_FALLBACKS: set = set()

# pack-plan cache: the resolver runs at least three times per job
# (builder init, abstract/init state, AOT prewarm) and each run used to
# re-trace the full model via jax.eval_shape(decoder.init) just to size
# buckets. ModelConfig is a frozen (hashable) dataclass, so the plan —
# a pure function of (config, dp, bucket_bytes, tie, mesh_axes) — is
# memoized on those inputs.
_PLAN_CACHE: Dict[Tuple, shd.PackPlan] = {}


def resolve_update_sharding(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    comm: Optional[shd.CommConfig],
    loss_fn: Optional[Callable] = None,
    offload_opt_state: bool = False,
) -> Tuple[bool, Optional[str], Optional[shd.PackPlan]]:
    """(active, fallback_reason, pack_plan) for a requested CommConfig.

    Update sharding is an optimization, not a semantics change, so an
    unsupported combination falls back to the replicated update with a
    recorded reason instead of failing the job. Supported meshes: any
    whose non-dp axes are confined to fsdp/tp — on a pure-dp mesh the
    whole step runs in one fully-manual region; with fsdp/tp in play
    the gradient exchange runs in a PARTIAL-manual region (manual over
    dp, fsdp/tp left to the auto partitioner) and the plan still packs
    GLOBAL leaf shapes, because auto-axis values appear global-shaped
    inside the region. Also required: built-in loss, f32 params,
    flat-compatible optimizer state (elementwise, or a plan-aware
    ``_flat_factory`` equivalent — optimizer.py's factored path), no
    MoE/host-offload. ``cfg.fp8`` composes on pure-dp meshes only, and
    quantized wire dtypes (bf16/int8) need the pure-dp full-manual
    region — their ``all_to_all`` cannot lower partial-manually.
    """
    if comm is None or not comm.update_sharding:
        return False, None, None
    dp = mesh.shape.get("dp", 1)
    others = sorted(
        a for a, s in mesh.shape.items() if a != "dp" and s > 1
    )
    unsupported = [a for a in others if a not in ("fsdp", "tp")]
    reason = None
    if dp <= 1:
        reason = "mesh has dp<=1"
    elif unsupported:
        reason = f"non-dp mesh axes beyond fsdp/tp in use: {unsupported}"
    elif cfg.n_experts > 0:
        reason = "MoE routing/aux losses not supported in the manual region"
    elif offload_opt_state:
        reason = "offload_opt_state keeps moments host-resident already"
    elif loss_fn is not None:
        reason = "custom loss_fn (denom override unavailable)"
    elif others and cfg.fp8:
        reason = (
            "fp8 delayed-scaling state threads the pure-dp manual "
            "region only (no carry across a partial-manual region)"
        )
    elif others and comm.wire_for(mesh, "dp") != "float32":
        reason = (
            "quantized wire dtypes need a pure-dp mesh (all_to_all "
            "over dp cannot lower inside the partial-manual region)"
        )
    mesh_axes = ("dp",) + tuple(others)
    plan = None
    if reason is None:
        cache_key: Optional[Tuple] = None
        try:
            cache_key = (
                cfg, dp, comm.bucket_bytes, cfg.tie_embeddings, mesh_axes
            )
            plan = _PLAN_CACHE.get(cache_key)
        except TypeError:  # unhashable config subclass: skip the cache
            cache_key = None
    if reason is None and plan is None:
        params_abs = jax.eval_shape(
            lambda: decoder.init(jax.random.key(0), cfg)
        )
        try:
            plan = shd.build_pack_plan(
                params_abs,
                dp,
                comm.bucket_bytes,
                tie_embeddings=cfg.tie_embeddings,
                mesh_axes=mesh_axes,
            )
            if cache_key is not None:
                _PLAN_CACHE[cache_key] = plan
        except ValueError as e:
            reason = str(e)
    if reason is None:
        reason = _probe_flat_optimizer(optimizer, plan)
    if reason is not None:
        key = (reason, getattr(cfg, "name", ""))
        if key not in _LOGGED_FALLBACKS:
            _LOGGED_FALLBACKS.add(key)
            logger.warning(
                "update sharding requested but falling back to the "
                "replicated update (config %s): %s",
                key[1] or "<unnamed>",
                reason,
            )
        return False, reason, None
    return True, None, plan


def _flat_opt_sharding(leaf, plan: shd.PackPlan, mesh: Mesh):
    if tuple(leaf.shape) == (plan.n_buckets, plan.bucket_elems):
        return NamedSharding(mesh, P(None, "dp"))
    return NamedSharding(mesh, P())


def abstract_train_state(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    rules=None,
    offload_opt_state: bool = False,
    comm: Optional[shd.CommConfig] = None,
):
    """``ShapeDtypeStruct`` tree matching ``init_train_state``'s output
    — shapes AND shardings — without materializing anything.

    Exists for AOT pre-compilation (train/prewarm.py): lowering the
    train step against abstract leaves requires the exact input
    shardings the live job will use, or the HLO (and therefore the
    persistent-cache key) diverges and the pre-warm buys nothing.

    ``offload_opt_state`` mirrors init's host-offload branch (moments
    born with pinned_host memory kinds). Low-bit (int8/int4) optimizer
    states are NOT supported: init leaves their quantized innards
    unconstrained (compiler-chosen shardings), which an AOT caller
    cannot reproduce deterministically — raise rather than silently
    pre-warm a key the live job will never hit.
    """
    param_shardings = shd.shardings_for_tree(
        mesh, decoder.logical_axes(cfg), rules
    )
    params_abs = jax.eval_shape(
        lambda: decoder.init(jax.random.key(0), cfg)
    )
    active, _, plan = resolve_update_sharding(
        cfg, mesh, optimizer, comm, offload_opt_state=offload_opt_state
    )
    if active:
        # ZeRO-1: the optimizer state lives on the flat bucketed view,
        # dp-sharded along the bucket axis (1/dp of the moments per
        # replica); params themselves stay in their usual shardings
        flat_opt = _effective_flat_optimizer(optimizer, plan)
        opt_abs = jax.eval_shape(flat_opt.init, _flat_abs(plan))
        rep = NamedSharding(mesh, P())
        shapes = {
            "params": params_abs,
            "opt_state": opt_abs,
            "step": jax.ShapeDtypeStruct((), jnp.int32),
        }
        sh = {
            "params": param_shardings,
            "opt_state": jax.tree.map(
                lambda l: _flat_opt_sharding(l, plan, mesh), opt_abs
            ),
            "step": rep,
        }
        if cfg.fp8:
            # pure-dp meshes never pipeline, so the delayed-scaling
            # state always rides the sharded step (replicated: the
            # histories are pmax-merged over dp every step)
            fp8_abs = jax.eval_shape(lambda: decoder.init_fp8_states(cfg))
            shapes["fp8"] = fp8_abs
            sh["fp8"] = jax.tree.map(lambda _: rep, fp8_abs)
        return jax.tree.map(
            lambda s, h: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=h),
            shapes,
            sh,
        )
    opt_abs = jax.eval_shape(optimizer.init, params_abs)
    if any(_is_quantized(leaf) for leaf in jax.tree.leaves(
            opt_abs, is_leaf=_is_quantized)):
        raise NotImplementedError(
            "abstract_train_state: low-bit optimizer states carry "
            "compiler-chosen shardings the AOT path cannot reproduce"
        )
    rep = NamedSharding(mesh, P())
    if offload_opt_state and jax.default_backend() != "cpu":
        opt_sh = _opt_state_host_shardings(
            opt_abs, params_abs, param_shardings, mesh
        )
    else:
        opt_sh = _map_param_subtrees(
            opt_abs,
            params_abs,
            param_shardings,
            param_leaf_fn=lambda leaf, s: s,
            other_fn=lambda sub: jax.tree.map(lambda _: rep, sub),
        )
    sh = {
        "params": param_shardings,
        "opt_state": opt_sh,
        "step": rep,
    }
    shapes = {
        "params": params_abs,
        "opt_state": opt_abs,
        "step": jax.ShapeDtypeStruct((), jnp.int32),
    }
    if cfg.fp8 and mesh.shape.get("pp", 1) == 1:
        fp8_abs = jax.eval_shape(lambda: decoder.init_fp8_states(cfg))
        sh["fp8"] = jax.tree.map(lambda _: rep, fp8_abs)
        shapes["fp8"] = fp8_abs
    return jax.tree.map(
        lambda s, h: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=h),
        shapes,
        sh,
    )


def state_shardings(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    rules=None,
    offload_opt_state: bool = False,
    comm: Optional[shd.CommConfig] = None,
):
    """The NamedSharding tree ``init_train_state`` produces (see
    ``abstract_train_state``, of which this is the shardings-only
    view)."""
    return jax.tree.map(
        lambda a: a.sharding,
        abstract_train_state(
            cfg, mesh, optimizer, rules, offload_opt_state, comm
        ),
    )


def init_train_state(
    rng: jax.Array,
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    rules=None,
    offload_opt_state: bool = False,
    comm: Optional[shd.CommConfig] = None,
) -> TrainState:
    """Jit-initialise params + optimizer state directly into their shardings.

    Parameters never materialise unsharded: init runs under jit with
    ``out_shardings`` derived from the logical-axis rules, so a 7B model
    initialises straight into per-device shards (contrast the reference's
    meta-init + rematerialisation dance, atorch fsdp_init_util.py).

    With ``comm.update_sharding`` resolved active, the optimizer state is
    born on the flat bucketed param view, dp-sharded (see
    ``resolve_update_sharding``); pass the SAME comm the step builder
    resolved (``TrainStepBuilder.comm_resolved``) so state layout and
    step agree.
    """
    param_shardings = shd.shardings_for_tree(
        mesh, decoder.logical_axes(cfg), rules
    )
    us_active, _, plan = resolve_update_sharding(
        cfg, mesh, optimizer, comm, offload_opt_state=offload_opt_state
    )
    if us_active:

        def f_us(rng):
            params = decoder.init(rng, cfg)
            params = jax.tree.map(
                jax.lax.with_sharding_constraint, params, param_shardings
            )
            flat = {"flat": shd.pack_flat(params, plan)}
            opt_state = _effective_flat_optimizer(optimizer, plan).init(
                flat
            )
            opt_state = jax.tree.map(
                lambda l: jax.lax.with_sharding_constraint(
                    l, _flat_opt_sharding(l, plan, mesh)
                ),
                opt_state,
            )
            state = {
                "params": params,
                "opt_state": opt_state,
                "step": jnp.zeros([], jnp.int32),
            }
            if cfg.fp8:
                state["fp8"] = decoder.init_fp8_states(cfg)
            return state

        return jax.jit(f_us)(rng)
    # optimizer-state leaves (Adam moments etc.) mirror param shapes and
    # must be born with the SAME shardings — otherwise every step starts
    # by involuntarily resharding the moments (XLA's "involuntary full
    # rematerialization" warning, a full moment-tree copy per step)
    def _constrain_like_params(opt_state, params):
        # optax state nests whole param-shaped subtrees (Adam mu/nu
        # etc.) — matched by structure via _map_param_subtrees.
        # Quantized states are left as-is: they are 4-8x smaller, so the
        # per-step reshard this guards against is proportionally cheap.
        return _map_param_subtrees(
            opt_state,
            params,
            param_shardings,
            param_leaf_fn=lambda leaf, s: leaf
            if _is_quantized(leaf)
            else jax.lax.with_sharding_constraint(leaf, s),
            other_fn=lambda sub: sub,
        )

    def f(rng):
        params = decoder.init(rng, cfg)
        params = jax.tree.map(
            jax.lax.with_sharding_constraint, params, param_shardings
        )
        opt_state = optimizer.init(params)
        opt_state = _constrain_like_params(opt_state, params)
        state = {
            "params": params,
            "opt_state": opt_state,
            "step": jnp.zeros([], jnp.int32),
        }
        if cfg.fp8 and mesh.shape.get("pp", 1) == 1:
            # fp8 delayed-scaling amax histories: tiny, replicated.
            # Pipeline meshes carry NO fp8 state: they run stateless
            # current scaling (decoder.run_trunk's "current" mode)
            state["fp8"] = decoder.init_fp8_states(cfg)
        return state

    if not (offload_opt_state and jax.default_backend() != "cpu"):
        return jax.jit(f)(rng)

    # offload: the moments must be BORN in host memory — a post-jit
    # transfer would still hit the fully-resident HBM peak, which is
    # exactly the case offload exists for. Two phases: params on device,
    # then optimizer.init jitted with host-kind out_shardings.
    def f_params(rng):
        params = decoder.init(rng, cfg)
        return jax.tree.map(
            jax.lax.with_sharding_constraint, params, param_shardings
        )

    def f_opt(params):
        # NO device-kind sharding constraints here — out_shardings below
        # fully pins placement AND host memory kind, so the moments never
        # materialize HBM-resident (the point of offloading)
        return optimizer.init(params)

    params = jax.jit(f_params)(rng)
    opt_shape = jax.eval_shape(f_opt, params)
    out_sh = _opt_state_host_shardings(
        opt_shape, params, param_shardings, mesh
    )
    opt_state = jax.jit(f_opt, out_shardings=out_sh)(params)
    state = {
        "params": params,
        "opt_state": opt_state,
        "step": jnp.zeros([], jnp.int32),
    }
    if cfg.fp8 and mesh.shape.get("pp", 1) == 1:
        state["fp8"] = jax.jit(lambda: decoder.init_fp8_states(cfg))()
    return state


class TrainStepBuilder:
    """Builds the jitted train step for (model config, mesh, strategy)."""

    def __init__(
        self,
        cfg: ModelConfig,
        mesh: Mesh,
        optimizer: optax.GradientTransformation,
        rules=None,
        grad_accum: int = 1,
        loss_fn: Optional[Callable] = None,
        attn_impl: str = "auto",
        offload_opt_state: bool = False,
        comm: Optional[shd.CommConfig] = None,
        health_sentinels: bool = False,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = optimizer
        self.rules = rules
        self.grad_accum = grad_accum
        self.attn_impl = attn_impl
        self.offload_opt_state = offload_opt_state
        self.comm = comm
        # in-graph numeric-health scalars appended to the step metrics
        # (observability/sentinels.py); rides the existing metrics
        # readback — no extra host syncs, no extra collectives beyond
        # widening the metric psum the sharded region already issues
        self.health_sentinels = health_sentinels
        # resolved ZeRO-1 state: active flag, fallback reason (None when
        # active or never requested), and the static flat pack layout
        self.update_sharding, self.update_sharding_reason, self._plan = (
            resolve_update_sharding(
                cfg,
                mesh,
                optimizer,
                comm,
                loss_fn=loss_fn,
                offload_opt_state=offload_opt_state,
            )
        )
        self._wire = (
            comm.wire_for(mesh, "dp") if self.update_sharding else None
        )
        # resolved mode ("zero1" defers the gradient exchange to one
        # reduce-scatter per step; "zero2" exchanges every microbatch so
        # only the 1/dp shard survives the accumulation loop) and the
        # transformation actually run on the flat view (the optimizer
        # itself, or its plan-aware flat equivalent for factored state)
        self.update_mode = comm.update_mode if self.update_sharding else ""
        self._flat_opt = (
            _effective_flat_optimizer(optimizer, self._plan)
            if self.update_sharding
            else None
        )
        # hybrid (dp×fsdp / dp×tp) update sharding: the partial-manual
        # region suppresses the model's internal constraints, so params
        # are re-pinned to their rule shardings after the flat unpack
        self._param_shardings = (
            shd.shardings_for_tree(mesh, decoder.logical_axes(cfg), rules)
            if self.update_sharding and len(self._plan.mesh_axes) > 1
            else None
        )
        if (
            offload_opt_state
            and _HOST is None
            and jax.default_backend() != "cpu"
        ):
            raise RuntimeError(
                "offload_opt_state needs the jax.memory.Space API; "
                "this jax build has no host memory space"
            )
        if cfg.remat in ("offload_attn", "save_qkv_offload"):
            from dlrover_tpu.common import jax_compat

            if not jax_compat.supports_activation_offload():
                # fail at builder construction, not deep in the remat
                # trace of the first step
                raise RuntimeError(
                    f"remat={cfg.remat!r} needs checkpoint_policies."
                    "save_and_offload_only_these_names, which this jax "
                    "build lacks; use save_qkv or full instead"
                )
        # switch-gating jitter needs a per-step rng; only the built-in
        # loss_fn accepts one (a custom loss_fn owns its rng handling)
        self._needs_rng = (
            loss_fn is None
            and cfg.n_experts > 0
            and cfg.moe_gating == "switch"
            and cfg.moe_jitter > 0.0
        )
        if cfg.fp8 and loss_fn is not None:
            raise ValueError(
                "cfg.fp8 threads fp8_states through the built-in "
                "loss_fn; a custom loss_fn cannot receive them"
            )
        self._loss_fn = loss_fn or functools.partial(
            decoder.loss_fn, cfg=cfg, mesh=mesh, attn_impl=attn_impl
        )

    def _grads(self, params, batch, rng=None, fp8=None):
        if self._needs_rng and rng is not None:
            loss_fn = functools.partial(self._loss_fn, rng=rng)
        else:
            loss_fn = self._loss_fn
        if fp8 == "current":
            # stateless current-scaling fp8 (pipeline meshes): nothing
            # to differentiate or thread — plain grads, no state out
            grad_fn = jax.value_and_grad(
                lambda p: loss_fn(p, batch, fp8_states="current"),
                has_aux=True,
            )
            (loss, metrics), grads = grad_fn(params)
            return loss, metrics, grads, None
        if fp8 is not None:
            # differentiate w.r.t. the fp8 state too: its "gradient" IS
            # the updated delayed-scaling state (ops/fp8.py convention)
            grad_fn = jax.value_and_grad(
                lambda p, f8: loss_fn(p, batch, fp8_states=f8),
                argnums=(0, 1),
                has_aux=True,
            )
            (loss, metrics), (grads, new_fp8) = grad_fn(params, fp8)
            return loss, metrics, grads, new_fp8
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (loss, metrics), grads = grad_fn(params, batch)
        return loss, metrics, grads, None

    def _accumulated_grads(self, params, batch, rng=None, fp8=None):
        """Microbatch scan: batch leading dim is [accum, micro_b, ...].

        fp8 delayed-scaling state advances ONCE per optimizer step, not
        once per microbatch: every microbatch quantizes against the
        SAME step-start scales (what an accum=1 step over the whole
        global batch would use), and the per-microbatch updated states
        merge by elementwise max. Each microbatch's new state is
        ``concat(hist[1:], amax_i)`` over the shared step-start history
        — all ≥ 0 — so the max is ``concat(hist[1:], max_i amax_i)``:
        exactly one history push carrying the global-batch amax,
        bitwise-matching the unfused single-step path (f32 max is
        exact). The stateless "current" mode has no carry entry."""
        a = self.grad_accum
        is_cur = fp8 == "current"

        def micro(carry, inp):
            mb, idx = inp
            if is_cur:
                g_acc, loss_acc = carry
                f8_acc = None
            else:
                g_acc, loss_acc, f8_acc = carry
            r = jax.random.fold_in(rng, idx) if rng is not None else None
            f8 = "current" if is_cur else fp8
            loss, _, g, new_f8 = self._grads(params, mb, rng=r, fp8=f8)
            g_acc = jax.tree.map(jnp.add, g_acc, g)
            if is_cur:
                return (g_acc, loss_acc + loss), None
            if fp8 is not None:
                f8_acc = jax.tree.map(jnp.maximum, f8_acc, new_f8)
            return (g_acc, loss_acc + loss, f8_acc), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        mb_batch = jax.tree.map(
            lambda x: x.reshape((a, x.shape[0] // a) + x.shape[1:]), batch
        )
        loss0 = jnp.zeros([], jnp.float32)
        # zeros are a safe max-identity: histories hold amaxes (>= 0)
        f8_zero = (
            None if fp8 is None else jax.tree.map(jnp.zeros_like, fp8)
        )
        init = (zeros, loss0) if is_cur else (zeros, loss0, f8_zero)
        out, _ = jax.lax.scan(micro, init, (mb_batch, jnp.arange(a)))
        grads, loss = out[0], out[1]
        new_fp8 = None if is_cur else out[2]
        grads = jax.tree.map(lambda g: g / a, grads)
        return loss / a, {"loss": loss / a}, grads, new_fp8

    @property
    def comm_resolved(self) -> Optional[shd.CommConfig]:
        """The CommConfig iff update sharding resolved active — pass this
        to ``init_train_state``/``state_shardings`` so the optimizer
        state is laid out for the step that will actually run."""
        return self.comm if self.update_sharding else None

    def _sentinel_metrics(
        self, params, updates, loss, new_fp8, new_opt, counts
    ) -> Dict[str, Any]:
        """The health-sentinel scalars for one step (see
        observability/sentinels.py for the key contract).  ``counts`` is
        the [5] grad-count vector — computed on the gradient tree in the
        replicated path, or inside the sharded region's packed psum so
        the reduction rides the existing collective."""
        out = snt.counts_to_metrics(counts, snt.static_size(params))
        out["sent_update_ratio"] = snt.update_ratio(updates, params)
        out["sent_loss_nonfinite"] = snt.loss_nonfinite(loss)
        if new_fp8 is not None:
            out["sent_fp8_sat"] = snt.fp8_saturation(new_fp8)
        skips = snt.sanitizer_count(new_opt)
        if skips is not None:
            out["sent_sanitizer_skips"] = skips
        return out

    def _sharded_step_fn(
        self, state: TrainState, batch
    ) -> Tuple[TrainState, Dict]:
        """ZeRO-1 step: reduce-scatter grads → 1/dp optimizer shard →
        all-gather params (arxiv 2004.13336).

        One full-manual shard_map region computes per-rank local grads
        (loss normalized by the psum'd GLOBAL token count, so cotangents
        match the data-parallel program bit-for-bit), packs them into
        the plan's fixed buckets, and reduce-scatters bucket-by-bucket
        (f32 wire = bitwise psum_scatter; bf16/int8 = all_to_all with
        f32 accumulation, blockwise scales for int8). The optimizer then
        runs OUTSIDE the region on the flat ``P(None, "dp")``-sharded
        view — clip/fused/state_dtype compose unchanged, the partitioner
        keeps every elementwise op local — and a second tiny manual
        region applies ``p + u`` per rank and all-gathers the result.

        fp8 (``cfg.fp8``): the delayed-scaling state enters the region
        replicated (``P()``), each rank differentiates w.r.t. it (its
        cotangent IS the updated state, ops/fp8.py convention), and the
        per-rank updated histories merge with ``lax.pmax`` over dp —
        per-rank state differs ONLY in the freshly-pushed slot (local
        activation/grad amax over this rank's tokens; the prefix and
        the weight amax are replicated), so the pmax yields exactly the
        global-batch amax the unsharded program observes with its
        all-reduce-max, keeping the f32 wire bitwise. Quantization
        scales come from the step-START history, so gradients are
        unaffected by the merge order. Under grad_accum the microbatch
        states merge by elementwise max first (same once-per-step
        semantics as ``_accumulated_grads``).

        Hybrid meshes (dp×fsdp / dp×tp): the gradient region goes
        PARTIAL-manual — manual over dp only, fsdp/tp left to the auto
        partitioner, which inserts the model-axis collectives exactly
        as in the replicated program. Auto-axis values appear
        global-shaped inside the region, so the pack plan and the
        bucket exchange are unchanged; only the region's lowering mode
        and the accumulation structure differ (the 0.4.x partitioner
        cannot partition a ``lax.scan`` whose carry touches auto-axis-
        sharded values inside a partial-manual region, so accumulation
        unrolls as a Python loop there).

        Modes: ``zero2`` (the boolean default) reduce-scatters every
        microbatch and accumulates 1/dp shards — no full-gradient
        buffer survives the accumulation loop, and on the f32 wire the
        rounding order matches the unsharded program (which all-reduces
        per microbatch). ``zero1`` accumulates the full local gradient
        and defers to ONE exchange per step — a×fewer collectives, at
        the cost of full-gradient residency and a different (still
        deterministic) summation order.
        """
        cfg, mesh, plan = self.cfg, self.mesh, self._plan
        a, wire = self.grad_accum, self._wire
        tie = cfg.tie_embeddings
        zoo = len(plan.mesh_axes) > 1
        defer = self.update_mode == "zero1"
        sent = self.health_sentinels
        fp8 = state.get("fp8") if cfg.fp8 else None
        if a > 1:
            # microbatch split OUTSIDE the region so the (rank,
            # microbatch) data assignment matches _accumulated_grads
            batch = jax.tree.map(
                lambda x: x.reshape((a, x.shape[0] // a) + x.shape[1:]),
                batch,
            )
            batch_spec = P(None, "dp")
        else:
            batch_spec = P("dp")

        def local_grads(params, f8, mb):
            mask = mb.get("mask")
            if mask is None:
                mask = jnp.ones_like(mb["targets"], dtype=jnp.float32)
            local_tokens = jnp.sum(mask.astype(jnp.float32))
            denom = jnp.maximum(jax.lax.psum(local_tokens, "dp"), 1.0)

            def lf(p, z, f):
                # the region flag makes shd.constrain a no-op and (when
                # tied) aliases the lm-head's table read to z, so the
                # head cotangent separates from the lookup's — the two
                # ride separate reduce-scatters exactly like GSPMD's two
                # all-reduces in the unsharded lowering
                with shd.update_sharding_region(
                    tie_zero=z, unroll_scans=zoo
                ):
                    return decoder.loss_fn(
                        p,
                        mb,
                        cfg=cfg,
                        mesh=mesh,
                        attn_impl=self.attn_impl,
                        denom=denom,
                        fp8_states=f,
                    )

            nf8 = None
            if tie:
                z = jnp.zeros(plan.shapes[0], jnp.float32)
                if f8 is not None:
                    (loss, metrics), (g, gz, nf8) = jax.value_and_grad(
                        lf, argnums=(0, 1, 2), has_aux=True
                    )(params, z, f8)
                else:
                    (loss, metrics), (g, gz) = jax.value_and_grad(
                        lambda p, z_: lf(p, z_, None),
                        argnums=(0, 1),
                        has_aux=True,
                    )(params, z)
            else:
                if f8 is not None:
                    (loss, metrics), (g, nf8) = jax.value_and_grad(
                        lambda p, f: lf(p, None, f),
                        argnums=(0, 1),
                        has_aux=True,
                    )(params, f8)
                else:
                    (loss, metrics), g = jax.value_and_grad(
                        lambda p: lf(p, None, None), has_aux=True
                    )(params)
                gz = None
            return loss, metrics, g, gz, nf8

        def exchange(g, gz):
            return shd.exchange_buckets(
                shd.pack_buckets(g, plan),
                plan,
                wire,
                axis="dp",
                tie_extra=gz if tie else None,
            )

        def region(params, f8, batch):
            if a > 1 and zoo:
                # UNROLLED microbatch loop: the 0.4.x partitioner dies
                # on a lax.scan touching auto-axis-sharded values inside
                # a partial-manual region, so hybrid meshes unroll.
                # zero2 exchanges per microbatch (shard-sized carry);
                # zero1 accumulates full local grads, one exchange.
                sh_acc = jnp.zeros(
                    (plan.n_buckets, plan.bucket_elems // plan.dp),
                    jnp.float32,
                )
                g_acc = gz_acc = None
                loss_acc = jnp.zeros([], jnp.float32)
                for i in range(a):
                    mb = jax.tree.map(lambda x: x[i], batch)
                    loss, _, g, gz, _ = local_grads(params, None, mb)
                    loss_acc = loss_acc + loss
                    if defer:
                        g_acc = (
                            g
                            if g_acc is None
                            else jax.tree.map(jnp.add, g_acc, g)
                        )
                        if tie:
                            gz_acc = gz if gz_acc is None else gz_acc + gz
                    else:
                        sh_acc = sh_acc + exchange(g, gz)
                shards = exchange(g_acc, gz_acc) if defer else sh_acc
                loc = {"loss": loss_acc}
                nf8 = None
            elif a > 1 and defer:
                # ZeRO-1 deferred exchange: accumulate the full local
                # gradient across the scan (like the replicated accum
                # path), then reduce-scatter ONCE — a×fewer collectives
                # than zero2, at full-gradient residency.
                def micro(carry, mb):
                    g_acc, gz_acc, loss_acc, f8_acc = carry
                    loss, _, g, gz, nf8 = local_grads(params, f8, mb)
                    g_acc = jax.tree.map(jnp.add, g_acc, g)
                    if tie:
                        gz_acc = gz_acc + gz
                    if f8 is not None:
                        f8_acc = jax.tree.map(jnp.maximum, f8_acc, nf8)
                    return (g_acc, gz_acc, loss_acc + loss, f8_acc), None

                init = (
                    jax.tree.map(jnp.zeros_like, params),
                    jnp.zeros(plan.shapes[0], jnp.float32) if tie else None,
                    jnp.zeros([], jnp.float32),
                    None if f8 is None else jax.tree.map(jnp.zeros_like, f8),
                )
                (g_acc, gz_acc, loss_acc, nf8), _ = jax.lax.scan(
                    micro, init, batch
                )
                shards = exchange(g_acc, gz_acc)
                loc = {"loss": loss_acc}
            elif a > 1:
                # zero2 (the boolean default): reduce-scatter EVERY
                # microbatch and accumulate the shards — the order the
                # unsharded program rounds in (GSPMD all-reduces each
                # microbatch's grads before the scan carry add), so the
                # f32 wire stays bitwise. Same collective count as the
                # baseline, half the bytes, and no full-gradient buffer
                # across the scan.
                def micro(carry, mb):
                    sh_acc, loss_acc, f8_acc = carry
                    loss, _, g, gz, nf8 = local_grads(params, f8, mb)
                    shards = exchange(g, gz)
                    if f8 is not None:
                        f8_acc = jax.tree.map(jnp.maximum, f8_acc, nf8)
                    return (sh_acc + shards, loss_acc + loss, f8_acc), None

                zeros = jnp.zeros(
                    (plan.n_buckets, plan.bucket_elems // plan.dp),
                    jnp.float32,
                )
                f8_zero = (
                    None
                    if f8 is None
                    else jax.tree.map(jnp.zeros_like, f8)
                )
                (shards, loss_acc, nf8), _ = jax.lax.scan(
                    micro,
                    (zeros, jnp.zeros([], jnp.float32), f8_zero),
                    batch,
                )
                loc = {"loss": loss_acc}
            else:
                _, loc, g, gz, nf8 = local_grads(params, f8, batch)
                shards = exchange(g, gz)
            if sent:
                # sentinel counts over THIS RANK's post-exchange shard of
                # the averaged gradient, packed with the metric scalars
                # into a single psum — the counts ride the metrics'
                # existing all-reduce instead of adding a collective.
                # Elementwise psum over the concatenation reduces each
                # lane exactly like a standalone scalar psum, so "loss"
                # stays bitwise identical to the sentinels-off lowering.
                cnt = snt.grad_counts(shards / a if a > 1 else shards)
                keys = list(loc)
                vec = jax.lax.psum(
                    jnp.concatenate(
                        [
                            jnp.stack(
                                [
                                    loc[k].astype(jnp.float32)
                                    for k in keys
                                ]
                            ),
                            cnt,
                        ]
                    ),
                    "dp",
                )
                metrics = {k: vec[i] for i, k in enumerate(keys)}
                metrics["_sent_counts"] = vec[len(keys):]
            else:
                metrics = {
                    k: jax.lax.psum(v, "dp") for k, v in loc.items()
                }
            if a > 1:
                metrics["loss"] = metrics["loss"] / a
            if f8 is not None:
                # global amax: per-rank states differ only in the new
                # slot (this rank's local amax); max over dp = the
                # unsharded program's all-reduce-max, exactly
                nf8 = jax.tree.map(
                    lambda h: jax.lax.pmax(h, "dp"), nf8
                )
            return metrics, shards, nf8

        sm_kwargs = {}
        if zoo:
            # partial-manual: dp is manual (the explicit psum_scatter /
            # psum collectives), fsdp/tp stay with the auto partitioner
            sm_kwargs["axis_names"] = {"dp"}
        metrics, grads_flat, new_fp8 = jax_compat.shard_map(
            region,
            mesh=mesh,
            in_specs=(P(), P(), batch_spec),
            out_specs=(P(), P(None, "dp"), P()),
            **sm_kwargs,
        )(state["params"], fp8, batch)
        if a > 1:
            # divide AFTER the exchange, where GSPMD's unsharded program
            # divides after its all-reduce — keeps the f32 wire bitwise
            grads_flat = grads_flat / a
        flat_sh = NamedSharding(mesh, P(None, "dp"))
        if zoo:
            # pin the flat stream's layout: the bucket axis dp-sharded,
            # replicated over fsdp/tp, so the optimizer sweep below is
            # purely elementwise-local (the HLO guard pins zero
            # cross-axis collectives on the moments)
            grads_flat = jax.lax.with_sharding_constraint(
                grads_flat, flat_sh
            )
        flat_params = {"flat": shd.pack_flat(state["params"], plan)}
        if zoo:
            flat_params["flat"] = jax.lax.with_sharding_constraint(
                flat_params["flat"], flat_sh
            )
        updates, new_opt = self._flat_opt.update(
            {"flat": grads_flat}, state["opt_state"], flat_params
        )
        def apply_region(fp, u):
            # per-rank `p + u` BEFORE the all-gather. Done in auto mode
            # the partitioner is free to gather `u` first, which splits
            # the optimizer's trailing `-lr * y` multiply from this add
            # and changes how the backend contracts the pair — a 1-ulp
            # params drift vs the unsharded step. Keeping the add inside
            # the manual region pins mult→add adjacency on every rank.
            idx = jax.lax.axis_index("dp")
            sh = u.shape[1]
            fp_shard = jax.lax.dynamic_slice(
                fp, (0, idx * sh), (fp.shape[0], sh)
            )
            return jax.lax.all_gather(
                fp_shard + u, "dp", axis=1, tiled=True
            )

        new_flat = jax_compat.shard_map(
            apply_region,
            mesh=mesh,
            in_specs=(P(), P(None, "dp")),
            out_specs=P(),
        )(flat_params["flat"], updates["flat"])
        params = shd.unpack_flat(new_flat, state["params"], plan)
        if zoo:
            # the region suppressed the model's internal constraints;
            # re-pin the unpacked params to their rule shardings so the
            # next step (and checkpointing) sees the canonical layout
            params = jax.tree.map(
                jax.lax.with_sharding_constraint,
                params,
                self._param_shardings,
            )
        metrics = dict(metrics)
        counts = metrics.pop("_sent_counts", None)
        metrics["grad_norm"] = optax.global_norm(grads_flat)
        if self.health_sentinels:
            metrics.update(
                self._sentinel_metrics(
                    state["params"],
                    updates,
                    metrics["loss"],
                    new_fp8,
                    new_opt,
                    counts,
                )
            )
        new_state = {
            "params": params,
            "opt_state": new_opt,
            "step": state["step"] + 1,
        }
        if fp8 is not None:
            new_state["fp8"] = new_fp8
        return new_state, metrics

    def step_fn(self, state: TrainState, batch) -> Tuple[TrainState, Dict]:
        if self.update_sharding:
            return self._sharded_step_fn(state, batch)
        batch = jax.tree.map(
            lambda x: shd.constrain(
                x, self.mesh, "batch", "seq", rules=self.rules
            )
            if x.ndim >= 2
            else x,
            batch,
        )
        rng = None
        if self._needs_rng:
            # deterministic per-step jitter key: same across hosts (SPMD
            # lockstep), different every step
            rng = jax.random.fold_in(jax.random.key(17), state["step"])
        fp8 = state.get("fp8")
        if (
            fp8 is None
            and self.cfg.fp8
            and self.mesh.shape.get("pp", 1) > 1
        ):
            # pipeline meshes: stateless current-scaling fp8 (delayed-
            # scaling state cannot thread a pipeline schedule; see
            # decoder.run_trunk)
            fp8 = "current"
        if self.grad_accum > 1:
            loss, metrics, grads, new_fp8 = self._accumulated_grads(
                state["params"], batch, rng=rng, fp8=fp8
            )
        else:
            loss, metrics, grads, new_fp8 = self._grads(
                state["params"], batch, rng=rng, fp8=fp8
            )
        opt_state = state["opt_state"]
        if self.offload_opt_state:
            # stream the moments HBM-ward only for the update; the jitted
            # step's output shardings put the new state back on host
            opt_state = _to_memory_kind(opt_state, _DEVICE)
        updates, new_opt = self.optimizer.update(
            grads, opt_state, state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        if self.offload_opt_state:
            new_opt = _to_memory_kind(new_opt, _HOST)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        if self.health_sentinels:
            metrics.update(
                self._sentinel_metrics(
                    state["params"],
                    updates,
                    loss,
                    new_fp8,
                    new_opt,
                    snt.grad_counts(grads),
                )
            )
        new_state = {
            "params": params,
            "opt_state": new_opt,
            "step": state["step"] + 1,
        }
        if new_fp8 is not None:
            new_state["fp8"] = new_fp8
        return new_state, metrics

    def build(self) -> Callable:
        """Return the jitted step with donated state."""
        return jax.jit(self.step_fn, donate_argnums=(0,))

    # ---- fused multi-step block -----------------------------------------

    def block_fn(
        self, state: TrainState, batches
    ) -> Tuple[TrainState, Dict]:
        """Run K train steps as ONE device program.

        ``batches`` leaves carry a leading block axis: [K, ...] (e.g.
        tokens [K, B, S]).  A ``lax.scan`` over that axis applies
        ``step_fn`` K times — microbatch accumulation, fp8 state
        threading, and remat policies all compose unchanged because the
        scan body IS ``step_fn``.  Per-step metrics (loss, grad_norm,
        spike inputs) come back STACKED as [K] arrays, so the host
        touches the device once per block instead of once per step:
        Python dispatch, metric readback, and callback cadence checks
        amortize over K steps (cf. TorchTitan's overlap-everything
        loop).  The per-step rng derivation keys off the step counter in
        the carry, so a fused block and K sequential calls see identical
        randomness.
        """
        return jax.lax.scan(self.step_fn, state, batches)

    def build_block(self) -> Callable:
        """Jitted K-step block with donated state.

        One compiled program per distinct K (the trainer shrinks K at
        cadence boundaries, so a handful of sizes compile over a run).
        """
        if self.offload_opt_state:
            # the per-step HBM<->host moment streaming inside a scan
            # body would serialize against the scan carry; run offloaded
            # states unfused instead of silently deoptimizing
            raise NotImplementedError(
                "fused train blocks do not compose with "
                "offload_opt_state; use block_k=1"
            )
        return jax.jit(self.block_fn, donate_argnums=(0,))


def build_eval_step(cfg: ModelConfig, mesh, rules=None, attn_impl="auto"):
    def eval_step(params, batch):
        _, metrics = decoder.loss_fn(
            params, batch, cfg=cfg, mesh=mesh, attn_impl=attn_impl
        )
        return metrics

    return jax.jit(eval_step)
