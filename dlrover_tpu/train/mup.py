"""muP — Maximal Update Parametrization (Tensor Programs V).

Reference surface being matched: atorch/atorch/mup/ (shape.py
get_shapes/zip_infshapes/set_base_shapes, init.py width-adjusted
initializers, optim.py MuAdam/MuSGD). The reference mutates torch modules
in place; the TPU-native shape is functional — infshapes are a pytree
computed from (base_params, params), inits are rescaled pure pytrees, and
the optimizers are optax transforms with per-leaf lr multipliers, which
jit/pjit compile away entirely.

Recipe (hidden = both dims grow with width, input = only fan_out grows,
output = only fan_in grows, vector = ≤1 dim):

               init std mult          Adam lr mult     SGD lr mult
  hidden       1/sqrt(fan_in_mult)    1/fan_in_mult    1
  input/vector 1                      1                fan_out_mult
  output       1/fan_in_mult          1/fan_in_mult    1/fan_in_mult

plus model-side rules (see models/decoder.py): attention scale 1/d_head
instead of 1/sqrt(d_head), and logits multiplied by 1/width_mult when
embeddings are tied (MuReadout).
"""

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class InfShape(NamedTuple):
    """Per-leaf shape annotated with its base (small-model) shape."""

    shape: Tuple[int, ...]
    base_shape: Tuple[int, ...]

    @property
    def inf_dims(self) -> Tuple[bool, ...]:
        return tuple(d != b for d, b in zip(self.shape, self.base_shape))

    @property
    def ninf(self) -> int:
        return sum(self.inf_dims)

    @property
    def kind(self) -> str:
        """hidden | input | output | vector (the muP weight classes).

        Matrix structure is read off the LAST two dims ([..., fan_in,
        fan_out] in the JAX kernel convention); leading dims (layer
        stacks, expert stacks) are batch dims and ignored.
        """
        if len(self.shape) < 2 or self.ninf == 0:
            return "vector"
        in_inf, out_inf = self.inf_dims[-2], self.inf_dims[-1]
        if in_inf and out_inf:
            return "hidden"
        if in_inf:
            return "output"
        if out_inf:
            return "input"
        return "vector"

    @property
    def fan_in_mult(self) -> float:
        if len(self.shape) < 2 or not self.inf_dims[-2]:
            return 1.0
        base = max(self.base_shape[-2], 1)
        return self.shape[-2] / base


def get_shapes(params) -> Any:
    """Pytree of shapes, savable as the base-shape spec (mup shape.py:20)."""
    return jax.tree.map(lambda p: tuple(jnp.shape(p)), params)


def zip_infshapes(base_shapes, params) -> Any:
    """Pair each leaf's shape with its base shape (mup shape.py:115).

    ``base_shapes`` is either a params pytree of the base-width model or
    the output of :func:`get_shapes` on it.
    """

    def make(b, p):
        bs = b if isinstance(b, tuple) else tuple(jnp.shape(b))
        ps = tuple(jnp.shape(p))
        if len(bs) != len(ps):
            raise ValueError(f"rank mismatch: base {bs} vs target {ps}")
        return InfShape(ps, bs)

    return jax.tree.map(make, base_shapes, params,
                        is_leaf=lambda x: isinstance(x, tuple))


def rescale_init(params, infshapes, *, readout_zero_init: bool = False):
    """Rescale a standard (1/sqrt(fan_in)-style) init to muP.

    A width-naive standard init already gives hidden matrices the right
    1/sqrt(fan_in) scaling, so hidden/input/vector leaves pass through;
    output-class leaves get the extra 1/sqrt(fan_in_mult) (taking their
    effective std from 1/sqrt(fan_in) to 1/fan_in at large width), or
    zeros when ``readout_zero_init`` (the paper's recommended readout).
    """

    def scale(p, s: InfShape):
        if s.kind != "output":
            return p
        if readout_zero_init:
            return jnp.zeros_like(p)
        return p / jnp.sqrt(jnp.asarray(s.fan_in_mult, p.dtype))

    return jax.tree.map(scale, params, infshapes,
                        is_leaf=lambda x: isinstance(x, InfShape))


def _lr_mults(infshapes, rule: str):
    def mult(s: InfShape) -> float:
        if rule == "adam":
            if s.kind in ("hidden", "output"):
                return 1.0 / s.fan_in_mult
            return 1.0
        # sgd
        if s.kind == "output":
            return 1.0 / s.fan_in_mult
        if s.kind in ("input", "vector"):
            # fan_out mult: the growth ratio of the last infinite dim
            for d, b, inf in zip(reversed(s.shape), reversed(s.base_shape),
                                 reversed(s.inf_dims)):
                if inf:
                    return d / max(b, 1)
            return 1.0
        return 1.0

    return jax.tree.map(mult, infshapes,
                        is_leaf=lambda x: isinstance(x, InfShape))


def scale_by_infshape(infshapes, rule: str = "adam"):
    """Optax transform applying per-leaf muP lr multipliers."""
    mults = _lr_mults(infshapes, rule)

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        return (
            jax.tree.map(lambda u, m: u * m, updates, mults),
            state,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def mu_adam(
    learning_rate,
    infshapes,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """MuAdam (mup optim.py): Adam with muP per-leaf lr scaling.

    Hyperparameters tuned at the base width transfer unchanged to any
    target width.
    """
    txs = [
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
        scale_by_infshape(infshapes, "adam"),
    ]
    if weight_decay:
        # decoupled wd AFTER the infshape scaling: muP wd is
        # width-independent for Adam, so it must not be divided by
        # fan_in_mult along with the Adam update
        txs.append(optax.add_decayed_weights(weight_decay))
    txs.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*txs)


def mu_sgd(
    learning_rate,
    infshapes,
    momentum: Optional[float] = None,
) -> optax.GradientTransformation:
    """MuSGD (mup optim.py): SGD with muP per-leaf lr scaling."""
    txs = []
    if momentum:
        txs.append(optax.trace(decay=momentum))
    txs.append(scale_by_infshape(infshapes, "sgd"))
    txs.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*txs)


def coord_check_stats(activations) -> Dict[str, float]:
    """Mean |activation| per leaf — the muP 'coordinate check' metric.

    Run at several widths: under muP these stay O(1) in width; under
    standard parametrization they grow/shrink with width.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(activations)
    return {
        jax.tree_util.keystr(path): float(jnp.abs(leaf).mean())
        for path, leaf in flat
    }
