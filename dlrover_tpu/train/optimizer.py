"""Optimizer factory (optax).

Covers the reference's optimizer surface (atorch/atorch/optimizers: AdamW
paths, AGD agd.py, WSAM wsam.py, BF16/low-bit optimizer states) with optax
transforms. Low-bit (int8) optimizer state lives in
``dlrover_tpu/ops/quant.py`` and is applied as an optax wrapper.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import optax
import optax.tree_utils as _otu


def warmup_cosine(
    peak_lr: float,
    warmup_steps: int = 100,
    decay_steps: int = 10000,
    end_lr_ratio: float = 0.1,
) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup_steps,
        decay_steps=decay_steps,
        end_value=peak_lr * end_lr_ratio,
    )


def build_schedule(
    name: str,
    peak_lr: float,
    warmup_steps: int = 100,
    decay_steps: int = 10000,
    end_lr_ratio: float = 0.1,
):
    """Named LR schedules (reference: atorch_trainer's HF-style
    lr_scheduler_type breadth — linear/cosine/constant/polynomial/
    inverse_sqrt). Returns an optax schedule fn, or the constant
    ``peak_lr`` for name="constant" without warmup."""
    if name == "warmup_cosine":
        return warmup_cosine(
            peak_lr, warmup_steps, decay_steps, end_lr_ratio
        )
    if name == "warmup_linear":
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, peak_lr, warmup_steps),
                optax.linear_schedule(
                    peak_lr, peak_lr * end_lr_ratio,
                    max(1, decay_steps - warmup_steps),
                ),
            ],
            [warmup_steps],
        )
    if name == "constant_with_warmup":
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, peak_lr, warmup_steps),
                optax.constant_schedule(peak_lr),
            ],
            [warmup_steps],
        )
    if name == "constant":
        return peak_lr
    if name == "polynomial":
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, peak_lr, warmup_steps),
                optax.polynomial_schedule(
                    peak_lr, peak_lr * end_lr_ratio, power=2.0,
                    transition_steps=max(1, decay_steps - warmup_steps),
                ),
            ],
            [warmup_steps],
        )
    if name == "inverse_sqrt":
        def sched(step):
            import jax.numpy as _jnp

            s = _jnp.maximum(step, 1)
            warm = peak_lr * s / max(warmup_steps, 1)
            decay = peak_lr * (max(warmup_steps, 1) / s) ** 0.5
            return _jnp.where(s < warmup_steps, warm, decay)

        return sched
    raise ValueError(f"unknown schedule {name!r}")


def _make_clip_fn(updates, grad_clip: float):
    """Per-leaf global-norm clip closure, numerically identical to
    ``optax.clip_by_global_norm(grad_clip)``: one global-norm
    reduction, then each leaf is scaled in its own dtype. Lets the
    fused/streamed optimizers fold clipping into their single state
    traversal instead of materializing a clipped gradient tree as a
    separate chain link."""
    if not grad_clip or grad_clip <= 0:
        return lambda g: g
    g_norm = optax.global_norm(updates)
    trigger = jnp.squeeze(g_norm < grad_clip)

    def clip_fn(g):
        return jax.lax.select(
            trigger, g, (g / g_norm.astype(g.dtype)) * grad_clip
        )

    return clip_fn


def fused_adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_clip: float = 0.0,
    state_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """Single-traversal AdamW: global-norm clipping, the moment
    updates, decoupled weight decay, and the lr scaling all happen in
    one walk over the gradient tree — one read and one write per
    optimizer-state leaf.

    Why: ``optax.chain(clip_by_global_norm, adamw)`` is four chained
    transforms (clip, scale_by_adam, add_decayed_weights, scale_by_lr),
    each materializing a full update tree between links. At 1.4B params
    that is ~11 GiB of optimizer state + gradients walked repeatedly in
    an HBM-bound phase of the step. Here the chain's per-leaf math is
    applied verbatim inside one tree.map, so XLA sees a single fused
    elementwise region per leaf and the state streams through VMEM
    once.

    Numerics match the optax chain EXACTLY (pinned in
    tests/test_fused_optimizer.py): the clip trigger/scale formula is
    ``clip_by_global_norm``'s, the moment/bias-correction arithmetic is
    ``scale_by_adam``'s (including the safe int32 count increment and
    the schedule reading the PRE-increment count), decay is
    ``add_decayed_weights``, the sign flip is ``scale_by_learning_rate``.

    ``state_dtype``: None (f32 moments, matching ``optax.adamw`` on f32
    params) | "bfloat16" (bf16 mu like ``mu_dtype=bfloat16``) |
    "factored" (delegates to ``factored_adamw`` with the clip folded
    into ITS single traversal).
    """
    if state_dtype == "factored":
        return factored_adamw(
            learning_rate, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay, grad_clip=grad_clip,
        )
    if state_dtype not in (None, "bfloat16"):
        raise ValueError(
            "fused_adamw supports state_dtype None/'bfloat16'/'factored'; "
            f"got {state_dtype!r} (quantized states keep their own fused "
            "streaming paths in ops/quant.py)"
        )
    mu_dtype = jnp.bfloat16 if state_dtype == "bfloat16" else None

    def _lr(step):
        return learning_rate(step) if callable(learning_rate) else learning_rate

    def init_fn(params):
        return {
            "step": jnp.zeros([], jnp.int32),
            # optax scale_by_adam state layout: mu in mu_dtype (param
            # dtype when None), nu in the param dtype
            "m": jax.tree.map(
                lambda p: jnp.zeros_like(p, mu_dtype or p.dtype), params
            ),
            "v": jax.tree.map(jnp.zeros_like, params),
        }

    def update_fn(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError("fused_adamw with weight_decay needs params")
        # optax numerics.safe_increment: saturate instead of wrapping
        max_t = jnp.iinfo(jnp.int32).max
        step = jnp.where(state["step"] < max_t, state["step"] + 1, max_t)
        # schedule parity with optax.scale_by_schedule: the lr for
        # update t reads schedule(count BEFORE increment)
        lr = _lr(state["step"])
        p_tree = params if params is not None else updates
        clip = _make_clip_fn(updates, grad_clip)

        def leaf(g, m, v, p):
            gc = clip(g)
            m2 = (1 - b1) * gc + b1 * m
            v2 = (1 - b2) * (gc * gc) + b2 * v
            # optax's tree_bias_correction is a jitted region, where
            # XLA rewrites the scalar divide to a reciprocal multiply;
            # route through it so eager parity is BITWISE, not 1-ulp
            mhat = _otu.tree_bias_correction(m2, b1, step)
            vhat = _otu.tree_bias_correction(v2, b2, step)
            u = mhat / (jnp.sqrt(vhat) + eps)
            if weight_decay:
                u = u + weight_decay * p
            if callable(learning_rate):
                u = jnp.array(-lr, dtype=u.dtype) * u
            else:
                u = -lr * u
            return u, m2.astype(mu_dtype) if mu_dtype else m2, v2

        out = jax.tree.map(
            leaf, updates, state["m"], state["v"], p_tree
        )
        is_triple = lambda x: isinstance(x, tuple)
        return (
            jax.tree.map(lambda o: o[0], out, is_leaf=is_triple),
            {
                "step": step,
                "m": jax.tree.map(lambda o: o[1], out, is_leaf=is_triple),
                "v": jax.tree.map(lambda o: o[2], out, is_leaf=is_triple),
            },
        )

    return optax.GradientTransformation(init_fn, update_fn)


def factored_adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    m_dtype=jnp.bfloat16,
    min_factored_size: int = 128,
    grad_clip: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW momentum + Adafactor-style factored second moment.

    For every matrix-shaped parameter the per-element variance nu is
    replaced by its rank-1 nonnegative factorization (row means R and
    column means C with v_hat = R*C / mean(R), exactly Adafactor's
    estimator, Shazeer & Stern 2018); vectors/scalars keep exact nu.
    First moment stays dense bf16 — this is the "Adafactor with
    momentum" / CAME family that trained T5 and PaLM.

    Why it exists here: on a 16 GiB v5e training 1.4B params, dense nu
    costs 2.7 GiB of HBM and ~5.4 GiB of optimizer bandwidth per step.
    Factoring frees both — the HBM buys the ``save_qkv_gate`` remat
    tier (models/decoder.py), the bandwidth shortens the optimizer
    phase outright. Reference capability analog: atorch low-bit states
    (low_bit/functional.py) compress nu 4x; factoring compresses it
    ~1000x with a weaker (but battle-tested) estimator.
    """

    def _lr(step):
        return learning_rate(step) if callable(learning_rate) else learning_rate

    def _factored(p) -> bool:
        return (
            p.ndim >= 2
            and p.shape[-1] >= min_factored_size
            and p.shape[-2] >= min_factored_size
        )

    def init_fn(params):
        def m0(p):
            return jnp.zeros_like(
                p, m_dtype if p.ndim >= 1 else jnp.float32
            )

        def v0(p):
            if _factored(p):
                return {
                    "r": jnp.zeros(p.shape[:-1], jnp.float32),
                    "c": jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32),
                }
            return jnp.zeros_like(p, jnp.float32)

        return {
            "step": jnp.zeros([], jnp.int32),
            "m": jax.tree.map(m0, params),
            "v": jax.tree.map(v0, params),
        }

    def update_fn(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError("factored_adamw with weight_decay needs params")
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        bc1 = 1 - b1**t
        bc2 = 1 - b2**t
        # schedule parity with optax.scale_by_schedule: the lr for
        # update t reads schedule(count BEFORE increment) — bias
        # correction uses the incremented count
        lr = _lr(state["step"])
        p_tree = params if params is not None else updates
        # grad_clip folded into this same traversal (fused_adamw path)
        clip = _make_clip_fn(updates, grad_clip)

        from dlrover_tpu.ops.quant import adamw_direction, adamw_m_ema

        def leaf(g, m, v, p):
            g32 = clip(g).astype(jnp.float32)
            m2 = adamw_m_ema(g32, m.astype(jnp.float32), b1)
            g2 = g32 * g32
            if isinstance(v, dict):
                r2 = b2 * v["r"] + (1 - b2) * jnp.mean(g2, axis=-1)
                c2 = b2 * v["c"] + (1 - b2) * jnp.mean(g2, axis=-2)
                # v_hat = outer(r, c) / mean(r): exact when nu is rank-1
                denom = jnp.maximum(jnp.mean(r2, axis=-1, keepdims=True),
                                    1e-30)
                vhat = (r2 / denom)[..., None] * c2[..., None, :]
                new_v = {"r": r2, "c": c2}
            else:
                vhat = b2 * v + (1 - b2) * g2
                new_v = vhat
            upd = adamw_direction(
                m2, vhat, bc1, bc2, eps, weight_decay,
                p.astype(jnp.float32) if weight_decay else None,
            )
            return (-lr * upd).astype(g.dtype), m2.astype(m.dtype), new_v

        # the v tree nests {"r","c"} dicts below the grads' leaf
        # positions — flatten_up_to collapses them back to one entry per
        # grad leaf so the trees zip despite the ragged structure
        gdef = jax.tree.structure(updates)
        g_leaves = gdef.flatten_up_to(updates)
        m_leaves = gdef.flatten_up_to(state["m"])
        v_leaves = gdef.flatten_up_to(state["v"])
        p_leaves = gdef.flatten_up_to(p_tree)
        out = [
            leaf(g, m, v, p)
            for g, m, v, p in zip(g_leaves, m_leaves, v_leaves, p_leaves)
        ]
        return (
            jax.tree.unflatten(gdef, [o[0] for o in out]),
            {
                "step": step,
                "m": jax.tree.unflatten(gdef, [o[1] for o in out]),
                "v": jax.tree.unflatten(gdef, [o[2] for o in out]),
            },
        )

    # advertise the plan-aware flat equivalent to the update-sharding
    # resolver (train_step._effective_flat_optimizer). Attached to the
    # init FUNCTION because GradientTransformation is a NamedTuple and
    # refuses attribute assignment.
    init_fn._flat_factory = lambda plan: flat_factored_adamw(
        plan,
        learning_rate,
        b1=b1,
        b2=b2,
        eps=eps,
        weight_decay=weight_decay,
        m_dtype=m_dtype,
        min_factored_size=min_factored_size,
        grad_clip=grad_clip,
    )
    return optax.GradientTransformation(init_fn, update_fn)


def flat_factored_adamw(
    plan,
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    m_dtype=jnp.bfloat16,
    min_factored_size: int = 128,
    grad_clip: float = 0.0,
) -> optax.GradientTransformation:
    """``factored_adamw`` reconstituted over a PackPlan's flat view.

    The ZeRO-1 update path hands the optimizer ONE leaf — the packed
    ``[n_buckets, bucket_elems]`` gradient stream — which a naively
    applied factored estimator would mis-factor (row/col means of the
    bucket matrix mean nothing). This transformation knows the pack
    layout: it rebuilds each parameter's view out of the flat stream
    (``flat.reshape(-1)[off:off+size].reshape(shape)``), runs
    ``factored_adamw``'s exact per-leaf math on the views, and repacks.

    State layout: the first moment stays ONE flat bf16
    ``[n_buckets, bucket_elems]`` leaf — flat-shaped, so the update
    sharding keeps it dp-sharded like the dense-Adam moments — while
    the second moment is a per-leaf tuple of Adafactor ``{"r","c"}``
    factor pairs (full f32 nu for leaves under ``min_factored_size``),
    replicated: the factors are the ~1000x-compressed part, so
    replicating them costs less than the bucket padding. Zero padding
    in the stream stays zero through the update (``m_ema`` and the
    repack both preserve it).
    """

    def _lr(step):
        return learning_rate(step) if callable(learning_rate) else learning_rate

    shapes, sizes, offsets = plan.shapes, plan.sizes, plan.offsets
    flat_shape = (plan.n_buckets, plan.bucket_elems)

    def _factored(shape) -> bool:
        return (
            len(shape) >= 2
            and shape[-1] >= min_factored_size
            and shape[-2] >= min_factored_size
        )

    def _views(flat):
        s = flat.reshape(-1)
        return [
            s[o : o + n].reshape(shp)
            for o, n, shp in zip(offsets, sizes, shapes)
        ]

    def _repack(leaves, dtype):
        # slice writes into zeros, not concatenate + pad: on jax 0.4.x a
        # concatenate mixing auto-axis-sharded operands with fresh zeros
        # comes back scaled by an unrelated mesh-axis size (see
        # parallel.sharding.pack_flat)
        flat = jnp.zeros((plan.padded,), dtype)
        off = 0
        for l in leaves:
            flat = jax.lax.dynamic_update_slice(
                flat, l.reshape(-1).astype(dtype), (off,)
            )
            off += int(l.size)
        return flat.reshape(flat_shape)

    def init_fn(flat_params):
        del flat_params  # layout comes from the plan, not the value
        v = []
        for shp in shapes:
            if _factored(shp):
                v.append(
                    {
                        "r": jnp.zeros(shp[:-1], jnp.float32),
                        "c": jnp.zeros(shp[:-2] + shp[-1:], jnp.float32),
                    }
                )
            else:
                v.append(jnp.zeros(shp, jnp.float32))
        return {
            "step": jnp.zeros([], jnp.int32),
            "m": jnp.zeros(flat_shape, m_dtype),
            "v": tuple(v),
        }

    def update_fn(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError(
                "flat_factored_adamw with weight_decay needs params"
            )
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        bc1 = 1 - b1**t
        bc2 = 1 - b2**t
        # schedule parity with optax.scale_by_schedule (see
        # factored_adamw): lr reads the PRE-increment count
        lr = _lr(state["step"])
        clip = _make_clip_fn(updates, grad_clip)

        from dlrover_tpu.ops.quant import adamw_direction, adamw_m_ema

        g_views = _views(clip(updates["flat"]))
        m_views = _views(state["m"])
        p_views = (
            _views(params["flat"]) if params is not None else g_views
        )
        upds, m2s, v2s = [], [], []
        for g, m, v, p in zip(g_views, m_views, state["v"], p_views):
            g32 = g.astype(jnp.float32)
            m2 = adamw_m_ema(g32, m.astype(jnp.float32), b1)
            g2 = g32 * g32
            if isinstance(v, dict):
                r2 = b2 * v["r"] + (1 - b2) * jnp.mean(g2, axis=-1)
                c2 = b2 * v["c"] + (1 - b2) * jnp.mean(g2, axis=-2)
                denom = jnp.maximum(
                    jnp.mean(r2, axis=-1, keepdims=True), 1e-30
                )
                vhat = (r2 / denom)[..., None] * c2[..., None, :]
                new_v = {"r": r2, "c": c2}
            else:
                vhat = b2 * v + (1 - b2) * g2
                new_v = vhat
            upd = adamw_direction(
                m2, vhat, bc1, bc2, eps, weight_decay,
                p.astype(jnp.float32) if weight_decay else None,
            )
            upds.append((-lr * upd).astype(jnp.float32))
            m2s.append(m2.astype(m_dtype))
            v2s.append(new_v)
        return (
            {"flat": _repack(upds, jnp.float32)},
            {
                "step": step,
                "m": _repack(m2s, m_dtype),
                "v": tuple(v2s),
            },
        )

    return optax.GradientTransformation(init_fn, update_fn)


def streamed_offload_adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_clip: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW whose moments live in pinned host memory, streamed per leaf.

    The legacy offload path (TrainStepBuilder.offload_opt_state) moves
    the WHOLE moment tree HBM-ward before the update — a transient
    device working set of 2x param bytes, exactly the peak offload
    exists to avoid (ADVICE r1 #1 / VERDICT r2 #8). Here the update
    walks the leaves in a serialized chain: each leaf's host->device
    transfer is data-dependent (via lax.optimization_barrier) on the
    previous leaf's computed update, so XLA cannot hoist the transfers
    together and the device-resident moment working set is bounded by
    the LARGEST LEAF (m+v), not the tree. accelerate/analyser.py models
    this bound for the `offload_opt` strategy tier.

    Drop-in for optax.adamw inside a chain (grad clipping composes in
    front). Moments are placed on host inside update_fn; pair with
    ``init_train_state(offload_opt_state=True)`` so they are BORN on
    host too. Reference capability: atorch's CPU-offload Adam
    (SURVEY §2.3 optimizers).
    """
    from dlrover_tpu.ops.quant import adamw_direction, adamw_moments

    from dlrover_tpu.common import jax_compat

    # None on jax builds without jax.memory: device_put(x, None) is then
    # a no-op placement, which matches the CPU-backend aliasing note above
    _host = jax_compat.HOST_MEMORY
    _dev = jax_compat.DEVICE_MEMORY

    def _lr(step):
        return learning_rate(step) if callable(learning_rate) else learning_rate

    def init_fn(params):
        zeros = lambda p: jnp.zeros_like(p, jnp.float32)
        return {
            "step": jnp.zeros([], jnp.int32),
            "m": jax.tree.map(zeros, params),
            "v": jax.tree.map(zeros, params),
        }

    def update_fn(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError(
                "streamed_offload_adamw with weight_decay needs params"
            )
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        bc1 = 1 - b1**t
        bc2 = 1 - b2**t
        # schedule parity with optax.scale_by_schedule: the lr for
        # update t reads schedule(count BEFORE increment) — bias
        # correction uses the incremented count
        lr = _lr(state["step"])
        p_tree = params if params is not None else updates

        gdef = jax.tree.structure(updates)
        g_leaves = jax.tree.leaves(updates)
        m_leaves = gdef.flatten_up_to(state["m"])
        v_leaves = gdef.flatten_up_to(state["v"])
        p_leaves = gdef.flatten_up_to(p_tree)

        # grad_clip folded into the streamed walk: the norm reduction
        # runs on the device-resident grads before any moment transfer
        clip = _make_clip_fn(updates, grad_clip)

        token = step.astype(jnp.float32)
        out_u, out_m, out_v = [], [], []
        for g, m_h, v_h, p in zip(g_leaves, m_leaves, v_leaves, p_leaves):
            # serialize THE TRANSFER: the host values only become
            # consumable after the previous leaf's token, so the
            # host->device copy cannot be hoisted to the front
            m_h, v_h, tok = jax.lax.optimization_barrier(
                (m_h, v_h, token)
            )
            m32 = jax.device_put(m_h, _dev)
            v32 = jax.device_put(v_h, _dev)
            g32 = clip(g).astype(jnp.float32)
            m2, v2 = adamw_moments(g32, m32, v32, b1, b2)
            upd = adamw_direction(
                m2, v2, bc1, bc2, eps, weight_decay,
                p.astype(jnp.float32) if weight_decay else None,
            )
            out_u.append((-lr * upd).astype(g.dtype))
            out_m.append(jax.device_put(m2, _host))
            out_v.append(jax.device_put(v2, _host))
            token = m2.ravel()[0] + tok * 0
        return (
            jax.tree.unflatten(gdef, out_u),
            {
                "step": step,
                "m": jax.tree.unflatten(gdef, out_m),
                "v": jax.tree.unflatten(gdef, out_v),
            },
        )

    return optax.GradientTransformation(init_fn, update_fn)


def agd(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """AGD optimizer (reference: atorch/optimizers/agd.py, NeurIPS'23).

    Auto-switches between gradient descent and adaptive step by comparing
    the gradient-difference preconditioner against ``delta``.
    """

    def init_fn(params):
        return {
            "step": jnp.zeros([], jnp.int32),
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params),
            "prev_g": jax.tree.map(jnp.zeros_like, params),
        }

    def update_fn(updates, state, params=None):
        step = state["step"] + 1
        t = step.astype(jnp.float32)

        def upd(g, m, v, pg):
            # gradient difference replaces the raw gradient in the second
            # moment — the AGD preconditioner.
            diff = g - b1 * pg
            m2 = b1 * m + (1 - b1) * g
            v2 = b2 * v + (1 - b2) * (diff * diff)
            mhat = m2 / (1 - b1**t)
            vhat = v2 / (1 - b2**t)
            denom = jnp.maximum(jnp.sqrt(vhat) / delta, 1.0)
            return -mhat / (denom * delta + eps), m2, v2, g

        flat = jax.tree.map(
            upd, updates, state["m"], state["v"], state["prev_g"]
        )
        out = jax.tree.map(lambda x: x[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        m = jax.tree.map(lambda x: x[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        v = jax.tree.map(lambda x: x[2], flat, is_leaf=lambda x: isinstance(x, tuple))
        pg = jax.tree.map(lambda x: x[3], flat, is_leaf=lambda x: isinstance(x, tuple))
        lr = learning_rate(step) if callable(learning_rate) else learning_rate
        out = jax.tree.map(lambda u: lr * u, out)
        return out, {"step": step, "m": m, "v": v, "prev_g": pg}

    return optax.GradientTransformation(init_fn, update_fn)


def wsam(
    base: optax.GradientTransformation,
    rho: float = 0.05,
    gamma: float = 0.9,
) -> optax.GradientTransformation:
    """Weighted Sharpness-Aware Minimization (reference:
    atorch/optimizers/wsam.py, KDD'23).

    Minimizes ``L + γ/(1-γ)·(L_sam − L)`` — γ interpolates vanilla descent
    (γ=0) through SAM (γ=0.5) to sharpness-dominated (γ→1). Implemented as
    an alternating two-phase transform (the optax-contrib SAM pattern):

    - even phase: cache params-point gradient, move to the adversarial
      point ``w + ρ·g/‖g‖`` (base state untouched);
    - odd phase: combine the cached and adversarial gradients into the
      WSAM gradient, step ``base`` with it from the *original* point
      (undoing the ascent offset in the same update).

    Each optimizer "step" therefore consumes two train-loop iterations /
    gradient evaluations, like the reference's closure-based torch impl.
    """
    if not 0.0 <= gamma < 1.0:
        raise ValueError(f"wsam gamma must be in [0, 1), got {gamma}")
    coef = gamma / (1.0 - gamma)

    def init_fn(params):
        return {
            "phase": jnp.zeros([], jnp.int32),
            "grad_cache": jax.tree.map(jnp.zeros_like, params),
            "ascent": jax.tree.map(jnp.zeros_like, params),
            "base": base.init(params),
        }

    def ascent_phase(updates, state, params):
        gnorm = optax.global_norm(updates)
        scale = rho / (gnorm + 1e-12)
        ascent = jax.tree.map(lambda g: g * scale, updates)
        return ascent, {
            "phase": state["phase"] + 1,
            "grad_cache": updates,
            "ascent": ascent,
            "base": state["base"],
        }

    def descent_phase(updates, state, params):
        g_w = jax.tree.map(
            lambda gs, g: g + coef * (gs - g), updates, state["grad_cache"]
        )
        step, base_state = base.update(g_w, state["base"], params)
        # net move: undo the ascent offset, then apply the base step
        out = jax.tree.map(lambda s, a: s - a, step, state["ascent"])
        return out, {
            "phase": state["phase"] + 1,
            "grad_cache": jax.tree.map(jnp.zeros_like, updates),
            "ascent": jax.tree.map(jnp.zeros_like, updates),
            "base": base_state,
        }

    def update_fn(updates, state, params=None):
        return jax.lax.cond(
            state["phase"] % 2 == 0,
            ascent_phase,
            descent_phase,
            updates,
            state,
            params,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def with_grad_sanitizer(
    tx: optax.GradientTransformation, mode: str
) -> optax.GradientTransformation:
    """Chain ``numeric.sanitize_grads(mode)`` IN FRONT of ``tx`` (the
    guard must see the raw gradients, before any clip rescales a spike
    into range).

    Keeps the wrapped optimizer reachable from the ZeRO update-sharding
    path: the sanitizer's state is a scalar counter (which the flat
    probe threads natively), and when ``tx`` advertises a plan-aware
    ``_flat_factory`` it is re-advertised with the same guard chained
    onto the flat stream — "skip"/"zero" act elementwise, so they mean
    the same thing on the packed ``[n_buckets, bucket_elems]`` view.
    """
    from dlrover_tpu.observability.numeric import sanitize_grads

    wrapped = optax.chain(sanitize_grads(mode), tx)
    factory = getattr(tx.init, "_flat_factory", None)
    if factory is not None:
        wrapped.init._flat_factory = lambda plan: optax.chain(
            sanitize_grads(mode), factory(plan)
        )
    return wrapped


def make_optimizer(
    name: str = "adamw",
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    decay_steps: int = 100000,
    schedule: str = "warmup_cosine",
    state_dtype: Optional[str] = None,
    offload_states: bool = False,
    fused: bool = False,
    sanitize_grads: Optional[str] = None,
) -> optax.GradientTransformation:
    """Build the training optimizer.

    ``state_dtype="bfloat16"`` keeps first/second moments in bf16
    (reference: atorch BF16Optimizer); ``"int8"`` uses the block-quantized
    states from ``ops/quant.py`` (reference: low_bit/functional.py);
    ``"mixed8"`` keeps bf16 momentum with int8 variance; ``"factored"``
    keeps bf16 momentum with an Adafactor-factored variance.
    ``offload_states=True`` (adamw only) keeps f32 moments in pinned
    host memory, streamed through HBM one leaf at a time
    (streamed_offload_adamw) — pair with
    ``init_train_state(offload_opt_state=True)``.
    ``fused=True`` (adamw only) folds the global-norm clip, weight
    decay and moment/param updates into one tree traversal
    (``fused_adamw``) — numerically identical to the chain, one read +
    one write per state leaf. Composes with state_dtype
    None/"bfloat16"/"factored" and with ``offload_states`` (the
    streamed walk absorbs the clip).
    ``sanitize_grads`` ("skip"/"zero") chains the non-finite gradient
    guard from ``observability/numeric.py`` in front of everything (see
    ``with_grad_sanitizer``).
    """
    if sanitize_grads is not None:
        return with_grad_sanitizer(
            make_optimizer(
                name=name,
                learning_rate=learning_rate,
                weight_decay=weight_decay,
                b1=b1,
                b2=b2,
                grad_clip=grad_clip,
                warmup_steps=warmup_steps,
                decay_steps=decay_steps,
                schedule=schedule,
                state_dtype=state_dtype,
                offload_states=offload_states,
                fused=fused,
            ),
            sanitize_grads,
        )
    if schedule in ("none", "const", "constant"):
        lr = learning_rate
    else:
        lr = build_schedule(
            schedule, learning_rate, warmup_steps, decay_steps
        )

    if fused and name != "adamw":
        raise ValueError(
            f"fused=True is an adamw fast path; got name={name!r}"
        )
    if fused and state_dtype not in (None, "bfloat16", "factored"):
        raise ValueError(
            "fused=True composes with state_dtype None/'bfloat16'/"
            f"'factored' (got {state_dtype!r}); the int8/int4/mixed "
            "paths already stream their own fused updates"
        )

    chain = []
    if grad_clip and grad_clip > 0 and not fused:
        chain.append(optax.clip_by_global_norm(grad_clip))

    if offload_states:
        if name != "adamw" or state_dtype is not None:
            raise ValueError(
                "offload_states streaming is implemented for plain adamw "
                "(f32 host moments); got name="
                f"{name!r} state_dtype={state_dtype!r}"
            )
        chain.append(
            streamed_offload_adamw(
                lr, b1=b1, b2=b2, weight_decay=weight_decay,
                grad_clip=grad_clip if fused else 0.0,
            )
        )
        return optax.chain(*chain)

    if fused:
        return fused_adamw(
            lr, b1=b1, b2=b2, weight_decay=weight_decay,
            grad_clip=grad_clip or 0.0, state_dtype=state_dtype,
        )

    if name == "adamw" and state_dtype == "factored":
        # Adafactor-factored nu + bf16 momentum (see factored_adamw):
        # ~2.7 GiB of HBM and ~5 GiB/step of bandwidth back at 1.4B
        inner = factored_adamw(
            lr, b1=b1, b2=b2, weight_decay=weight_decay
        )
        chain.append(inner)
        tx = optax.chain(*chain)
        # re-advertise the flat factory through the chain wrapper so the
        # update-sharding probe still sees it; the clip link re-wraps as
        # clip-on-the-flat-stream (same global norm — padding is zero)
        inner_factory = inner.init._flat_factory
        if grad_clip and grad_clip > 0:
            tx.init._flat_factory = lambda plan: optax.chain(
                optax.clip_by_global_norm(grad_clip),
                inner_factory(plan),
            )
        else:
            tx.init._flat_factory = inner_factory
        return tx

    if name == "adamw" and state_dtype in ("mixed8", "mixed4"):
        # bf16 momentum + int8/int4 blockwise variance: frees ~75% of
        # nu's HBM with Adafactor-grade variance fidelity; cheaper per
        # step than bf16 nu (less optimizer bandwidth). The bench's
        # save_qkv_gate remat tier exists because of this headroom.
        from dlrover_tpu.ops.quant import mixed_adamw

        chain.append(
            mixed_adamw(
                lr,
                b1=b1,
                b2=b2,
                weight_decay=weight_decay,
                v_bits=8 if state_dtype == "mixed8" else 4,
            )
        )
        return optax.chain(*chain)

    if name == "adamw" and state_dtype in ("int8", "int4"):
        # fused streaming path: chunked dequant-update-requant keeps the
        # float32 working set O(chunk) — the generic wrapper below would
        # materialise full f32 moments every step (OOM at >=1B params)
        from dlrover_tpu.ops.quant import lowbit_adamw

        chain.append(
            lowbit_adamw(
                lr,
                b1=b1,
                b2=b2,
                weight_decay=weight_decay,
                bits=8 if state_dtype == "int8" else 4,
            )
        )
        return optax.chain(*chain)

    if name == "adamw":
        mu_dtype = None
        if state_dtype == "bfloat16":
            mu_dtype = jnp.bfloat16
        chain.append(
            optax.adamw(
                lr, b1=b1, b2=b2, weight_decay=weight_decay, mu_dtype=mu_dtype
            )
        )
    elif name == "adam":
        chain.append(optax.adam(lr, b1=b1, b2=b2))
    elif name == "agd":
        chain.append(agd(lr if callable(lr) else (lambda s: lr), b1=b1, b2=b2))
        if weight_decay:
            chain.append(optax.add_decayed_weights(-weight_decay))
    elif name == "sgd":
        chain.append(optax.sgd(lr, momentum=0.9))
    elif name == "lion":
        chain.append(optax.lion(lr, weight_decay=weight_decay))
    elif name == "wsam":
        chain.append(
            wsam(
                optax.adamw(
                    lr, b1=b1, b2=b2, weight_decay=weight_decay
                )
            )
        )
    else:
        raise ValueError(f"unknown optimizer {name}")

    if state_dtype in ("int8", "int4"):
        if name == "wsam":
            # quantizing wsam's ascent/grad_cache leaves would subtract a
            # lossy ascent from the exact one applied to params, leaking
            # quantization error straight into the weights every 2 steps
            raise ValueError(
                "wsam is incompatible with low-bit optimizer state; use "
                "state_dtype=None or 'bfloat16'"
            )
        from dlrover_tpu.ops.quant import quantize_optimizer_state

        bits = 8 if state_dtype == "int8" else 4
        return quantize_optimizer_state(optax.chain(*chain), bits=bits)
    return optax.chain(*chain)


def opt_state_bytes_per_replica(opt_state) -> int:
    """Bytes of optimizer state ONE data-parallel replica holds.

    Leaves carrying a sharding count only their per-device shard (the
    ZeRO-1 flat moments are ``P(None, "dp")``-sharded, so each replica
    holds 1/dp of them); replicated or host-side leaves count in full.
    Works on live arrays and on ``jax.eval_shape``/abstract states with
    ``.sharding`` attached.
    """
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(opt_state):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:
                pass
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total
