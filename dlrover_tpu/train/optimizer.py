"""Optimizer factory (optax).

Covers the reference's optimizer surface (atorch/atorch/optimizers: AdamW
paths, AGD agd.py, WSAM wsam.py, BF16/low-bit optimizer states) with optax
transforms. Low-bit (int8) optimizer state lives in
``dlrover_tpu/ops/quant.py`` and is applied as an optax wrapper.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import optax


def warmup_cosine(
    peak_lr: float,
    warmup_steps: int = 100,
    decay_steps: int = 10000,
    end_lr_ratio: float = 0.1,
) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup_steps,
        decay_steps=decay_steps,
        end_value=peak_lr * end_lr_ratio,
    )


def agd(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """AGD optimizer (reference: atorch/optimizers/agd.py, NeurIPS'23).

    Auto-switches between gradient descent and adaptive step by comparing
    the gradient-difference preconditioner against ``delta``.
    """

    def init_fn(params):
        return {
            "step": jnp.zeros([], jnp.int32),
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params),
            "prev_g": jax.tree.map(jnp.zeros_like, params),
        }

    def update_fn(updates, state, params=None):
        step = state["step"] + 1
        t = step.astype(jnp.float32)

        def upd(g, m, v, pg):
            # gradient difference replaces the raw gradient in the second
            # moment — the AGD preconditioner.
            diff = g - b1 * pg
            m2 = b1 * m + (1 - b1) * g
            v2 = b2 * v + (1 - b2) * (diff * diff)
            mhat = m2 / (1 - b1**t)
            vhat = v2 / (1 - b2**t)
            denom = jnp.maximum(jnp.sqrt(vhat) / delta, 1.0)
            return -mhat / (denom * delta + eps), m2, v2, g

        flat = jax.tree.map(
            upd, updates, state["m"], state["v"], state["prev_g"]
        )
        out = jax.tree.map(lambda x: x[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        m = jax.tree.map(lambda x: x[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        v = jax.tree.map(lambda x: x[2], flat, is_leaf=lambda x: isinstance(x, tuple))
        pg = jax.tree.map(lambda x: x[3], flat, is_leaf=lambda x: isinstance(x, tuple))
        lr = learning_rate(step) if callable(learning_rate) else learning_rate
        out = jax.tree.map(lambda u: lr * u, out)
        return out, {"step": step, "m": m, "v": v, "prev_g": pg}

    return optax.GradientTransformation(init_fn, update_fn)


def wsam(
    base: optax.GradientTransformation,
    rho: float = 0.05,
    gamma: float = 0.9,
) -> optax.GradientTransformation:
    """Weighted Sharpness-Aware Minimization (reference:
    atorch/optimizers/wsam.py, KDD'23).

    Minimizes ``L + γ/(1-γ)·(L_sam − L)`` — γ interpolates vanilla descent
    (γ=0) through SAM (γ=0.5) to sharpness-dominated (γ→1). Implemented as
    an alternating two-phase transform (the optax-contrib SAM pattern):

    - even phase: cache params-point gradient, move to the adversarial
      point ``w + ρ·g/‖g‖`` (base state untouched);
    - odd phase: combine the cached and adversarial gradients into the
      WSAM gradient, step ``base`` with it from the *original* point
      (undoing the ascent offset in the same update).

    Each optimizer "step" therefore consumes two train-loop iterations /
    gradient evaluations, like the reference's closure-based torch impl.
    """
    if not 0.0 <= gamma < 1.0:
        raise ValueError(f"wsam gamma must be in [0, 1), got {gamma}")
    coef = gamma / (1.0 - gamma)

    def init_fn(params):
        return {
            "phase": jnp.zeros([], jnp.int32),
            "grad_cache": jax.tree.map(jnp.zeros_like, params),
            "ascent": jax.tree.map(jnp.zeros_like, params),
            "base": base.init(params),
        }

    def ascent_phase(updates, state, params):
        gnorm = optax.global_norm(updates)
        scale = rho / (gnorm + 1e-12)
        ascent = jax.tree.map(lambda g: g * scale, updates)
        return ascent, {
            "phase": state["phase"] + 1,
            "grad_cache": updates,
            "ascent": ascent,
            "base": state["base"],
        }

    def descent_phase(updates, state, params):
        g_w = jax.tree.map(
            lambda gs, g: g + coef * (gs - g), updates, state["grad_cache"]
        )
        step, base_state = base.update(g_w, state["base"], params)
        # net move: undo the ascent offset, then apply the base step
        out = jax.tree.map(lambda s, a: s - a, step, state["ascent"])
        return out, {
            "phase": state["phase"] + 1,
            "grad_cache": jax.tree.map(jnp.zeros_like, updates),
            "ascent": jax.tree.map(jnp.zeros_like, updates),
            "base": base_state,
        }

    def update_fn(updates, state, params=None):
        return jax.lax.cond(
            state["phase"] % 2 == 0,
            ascent_phase,
            descent_phase,
            updates,
            state,
            params,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(
    name: str = "adamw",
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    decay_steps: int = 100000,
    schedule: str = "warmup_cosine",
    state_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """Build the training optimizer.

    ``state_dtype="bfloat16"`` keeps first/second moments in bf16
    (reference: atorch BF16Optimizer); ``"int8"`` uses the block-quantized
    states from ``ops/quant.py`` (reference: low_bit/functional.py).
    """
    if schedule == "warmup_cosine":
        lr = warmup_cosine(learning_rate, warmup_steps, decay_steps)
    else:
        lr = learning_rate

    chain = []
    if grad_clip and grad_clip > 0:
        chain.append(optax.clip_by_global_norm(grad_clip))

    if name == "adamw" and state_dtype in ("int8", "int4"):
        # fused streaming path: chunked dequant-update-requant keeps the
        # float32 working set O(chunk) — the generic wrapper below would
        # materialise full f32 moments every step (OOM at >=1B params)
        from dlrover_tpu.ops.quant import lowbit_adamw

        chain.append(
            lowbit_adamw(
                lr,
                b1=b1,
                b2=b2,
                weight_decay=weight_decay,
                bits=8 if state_dtype == "int8" else 4,
            )
        )
        return optax.chain(*chain)

    if name == "adamw":
        mu_dtype = None
        if state_dtype == "bfloat16":
            mu_dtype = jnp.bfloat16
        chain.append(
            optax.adamw(
                lr, b1=b1, b2=b2, weight_decay=weight_decay, mu_dtype=mu_dtype
            )
        )
    elif name == "adam":
        chain.append(optax.adam(lr, b1=b1, b2=b2))
    elif name == "agd":
        chain.append(agd(lr if callable(lr) else (lambda s: lr), b1=b1, b2=b2))
        if weight_decay:
            chain.append(optax.add_decayed_weights(-weight_decay))
    elif name == "sgd":
        chain.append(optax.sgd(lr, momentum=0.9))
    elif name == "lion":
        chain.append(optax.lion(lr, weight_decay=weight_decay))
    elif name == "wsam":
        chain.append(
            wsam(
                optax.adamw(
                    lr, b1=b1, b2=b2, weight_decay=weight_decay
                )
            )
        )
    else:
        raise ValueError(f"unknown optimizer {name}")

    if state_dtype in ("int8", "int4"):
        if name == "wsam":
            # quantizing wsam's ascent/grad_cache leaves would subtract a
            # lossy ascent from the exact one applied to params, leaking
            # quantization error straight into the weights every 2 steps
            raise ValueError(
                "wsam is incompatible with low-bit optimizer state; use "
                "state_dtype=None or 'bfloat16'"
            )
        from dlrover_tpu.ops.quant import quantize_optimizer_state

        bits = 8 if state_dtype == "int8" else 4
        return quantize_optimizer_state(optax.chain(*chain), bits=bits)
    return optax.chain(*chain)
