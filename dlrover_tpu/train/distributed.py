"""Worker-side distributed bootstrap.

The agent hands the sealed rendezvous world to the worker via env vars;
``init_distributed()`` turns them into a ``jax.distributed`` cluster so
every host's chips join one global device mesh. Reference analog: the
torch-elastic worker picking up MASTER_ADDR/RANK env and NCCL init
(elastic_agent/torch/training.py worker spec), replaced by XLA's
coordination service over DCN.
"""

import os
from typing import Optional

import jax

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialise jax.distributed from agent-provided env. Idempotent.

    Returns True if a multi-process cluster was formed.
    """
    coordinator = coordinator or os.environ.get("DLROVER_TPU_COORDINATOR", "")
    num_processes = num_processes or int(
        os.environ.get("DLROVER_TPU_NUM_PROCESSES", "1")
    )
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ.get("DLROVER_TPU_PROCESS_ID", "0"))
    )
    if num_processes <= 1 or not coordinator:
        logger.info("single-process run; skipping jax.distributed")
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed up: process %d/%d, %d global devices",
        process_id,
        num_processes,
        len(jax.devices()),
    )
    return True


def shutdown_distributed():
    try:
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001
        pass


def global_chip_count() -> int:
    return len(jax.devices())


def process_index() -> int:
    return jax.process_index()
