"""Estimator-style executor for the sparse (PS) training tier.

This closes the one remaining reference row: the TF estimator trainer
with TF_CONFIG failover. Reference surface:

- ``EstimatorExecutor``
  (dlrover/trainer/tensorflow/executor/estimator_executor.py:52):
  synthesizes TF_CONFIG, builds the user estimator, wires the default
  hooks (global-step report, elastic data-shard report, checkpoint
  saver), runs ``train_and_evaluate`` with a BestExporter.
- ``TensorflowFailover`` / ``FailoverClient``
  (dlrover/trainer/tensorflow/failover/tensorflow_failover.py:33,
  failover/failover_client.py:21): a monitor thread polls the master's
  PS cluster version; "migrating"/"scaling" changes refresh TF_CONFIG
  and checkpoint-then-rebuild the session; "ps_failure" exits the
  worker so the agent restarts it from the last checkpoint.
- ``FileReader`` + ``ColumnInfo``
  (dlrover/trainer/tensorflow/reader/file_reader.py,
  util/column_info.py): schema'd CSV reading fed by the master's
  dynamic data shards.
- Hooks (dlrover/trainer/tensorflow/hooks/): per-run-step callbacks —
  ``GlobalStepHook``, ``ElasticDataShardReportHook``.

TPU-native framing — deliberately NOT a session rebuild design:

- There is no TF session to tear down.  The "PS set" is the sparse
  tier's versioned KvServer ring (sparse/server.py); a *planned*
  membership change (scale-out/in, migration) is adopted **live** by
  re-routing the HRW ring with bounded key migration — training does
  not stop, which strictly dominates the reference's
  checkpoint-and-rebuild on the same event.
- An *unplanned* change (a server crashed: its rows are gone) is the
  reference's "ps_failure".  The monitor detects it when migration
  export hits a dead socket, adopts the new ring without migration, and
  the estimator restores the sparse tier from the latest checkpoint —
  the same restore the reference reaches via worker exit + agent
  restart, minus the process churn.
- TF_CONFIG becomes a plain ``ClusterSpec`` synthesized from the
  master (PS names from ElasticPsService, addresses from the KV store)
  or injected via ``DLROVER_TPU_CLUSTER_SPEC`` for operator-launched
  pods (the ``set_tf_config``/``wait_for_tf_config`` entry points).

The model contract is duck-typed the way the executor's
``classifier_class`` is: ``model_fn(mode, params, cluster)`` returns an
object with ``train_step(features, labels) -> loss``, ``eval_metrics(
features, labels) -> dict``, ``save(dir)``/``restore(dir)`` and an
optional ``coll`` (a sparse DistributedEmbedding) that failover should
re-route.  models/deepfm.DeepFM fits with a two-line adapter.
"""

import json
import os
import shutil
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

CLUSTER_SPEC_ENV = "DLROVER_TPU_CLUSTER_SPEC"


class ModeKeys:
    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "predict"


# ---------------------------------------------------------------------------
# Schema'd file reading (reference: reader/file_reader.py, util/column_info.py)
# ---------------------------------------------------------------------------


@dataclass
class ColumnInfo:
    """One input column.  dtype: "int64" | "float32" | "string"."""

    name: str
    dtype: str = "float32"
    is_label: bool = False


def _cast(values: List[str], dtype: str) -> np.ndarray:
    if dtype == "int64":
        return np.asarray(values, dtype=np.int64)
    if dtype == "float32":
        return np.asarray(values, dtype=np.float32)
    if dtype == "string":
        return np.asarray(values, dtype=object)
    raise ValueError(f"unknown column dtype {dtype!r}")


class FileReader:
    """Line-oriented delimited-text reader producing (features, labels)
    batches, optionally fed by the master's dynamic data shards.

    Without a ``shard_client`` it reads the whole file once per
    ``__iter__`` (one epoch).  With one, it consumes master-issued
    shards (``ShardingClient.fetch_shard``) so failed workers' shards
    are re-queued — completion is reported per consumed batch via
    ``report_batch_done`` (the estimator wires the
    ``ElasticDataShardReportHook`` for that, exactly like the reference
    executor does at estimator_executor.py:163-172), or by the reader
    itself when ``auto_report=True`` (hook-less use).
    """

    def __init__(
        self,
        path: str,
        columns: List[ColumnInfo],
        batch_size: int,
        sep: str = ",",
        skip_header: bool = False,
        shuffle: bool = False,
        seed: int = 0,
        shard_client=None,
        auto_report: bool = False,
    ):
        self.path = path
        self.columns = columns
        self.batch_size = int(batch_size)
        self.sep = sep
        self.skip_header = skip_header
        self.shuffle = shuffle
        self.seed = seed
        self.shard_client = shard_client
        self.auto_report = auto_report
        self._rng = np.random.default_rng(seed)
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
        if skip_header and lines:
            lines = lines[1:]
        self._lines = [ln for ln in lines if ln.strip()]

    @property
    def num_records(self) -> int:
        return len(self._lines)

    def _batch(self, rows: List[str]) -> Tuple[Dict[str, np.ndarray], Any]:
        cols: List[List[str]] = [[] for _ in self.columns]
        for ln in rows:
            parts = ln.split(self.sep)
            if len(parts) != len(self.columns):
                raise ValueError(
                    f"row has {len(parts)} fields, schema has "
                    f"{len(self.columns)}: {ln!r}"
                )
            for i, v in enumerate(parts):
                cols[i].append(v)
        features: Dict[str, np.ndarray] = {}
        labels = None
        for ci, values in zip(self.columns, cols):
            arr = _cast(values, ci.dtype)
            if ci.is_label:
                labels = arr
            else:
                features[ci.name] = arr
        return features, labels

    def _iter_indices(self) -> Iterator[List[int]]:
        if self.shard_client is None:
            idx = np.arange(len(self._lines))
            if self.shuffle:
                self._rng.shuffle(idx)
            for lo in range(0, len(idx), self.batch_size):
                yield idx[lo : lo + self.batch_size].tolist()
            return
        # master-issued shards; batches never span shards so per-batch
        # completion reporting can close each shard exactly
        while True:
            shard = self.shard_client.fetch_shard()
            if shard is None:
                return
            start, end, record_indices = shard
            idx = (
                list(record_indices)
                if record_indices
                else list(range(start, end))
            )
            if self.shuffle:
                self._rng.shuffle(idx)
            for lo in range(0, len(idx), self.batch_size):
                batch = idx[lo : lo + self.batch_size]
                yield batch
                if self.auto_report:
                    self.shard_client.report_batch_done(len(batch))

    def __iter__(self) -> Iterator[Tuple[Dict[str, np.ndarray], Any]]:
        for batch_idx in self._iter_indices():
            rows = [self._lines[i] for i in batch_idx]
            feats, labels = self._batch(rows)
            self._last_batch_len = len(rows)
            yield feats, labels


# ---------------------------------------------------------------------------
# Cluster spec (the TF_CONFIG analog)
# ---------------------------------------------------------------------------


@dataclass
class ClusterSpec:
    """``cluster`` maps role → member names/addresses; ``task`` is this
    process.  Synthesized from the master or injected via env
    (reference: base_executor.get_cluster_info_by_tf_config +
    pod_scaler.new_tf_config)."""

    cluster: Dict[str, List[str]] = field(default_factory=dict)
    task_type: str = "worker"
    task_index: int = 0

    @property
    def is_chief(self) -> bool:
        # reference chief semantics: the chief role, else worker 0 when
        # no explicit chief is declared
        if self.task_type == "chief":
            return True
        return (
            self.task_type == "worker"
            and self.task_index == 0
            and not self.cluster.get("chief")
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "cluster": self.cluster,
                "task": {"type": self.task_type, "index": self.task_index},
            }
        )

    @classmethod
    def from_json(cls, raw: str) -> "ClusterSpec":
        obj = json.loads(raw)
        task = obj.get("task", {})
        return cls(
            cluster=dict(obj.get("cluster", {})),
            task_type=task.get("type", "worker"),
            task_index=int(task.get("index", 0)),
        )


def set_cluster_spec(spec) -> None:
    """Inject the cluster spec (reference: EstimatorExecutor.set_tf_config)."""
    if isinstance(spec, ClusterSpec):
        raw = spec.to_json()
    elif isinstance(spec, str):
        raw = spec
    else:
        raw = json.dumps(spec)
    os.environ[CLUSTER_SPEC_ENV] = raw


def wait_for_cluster_spec(
    timeout_s: float = 300.0, poll_s: float = 1.0
) -> ClusterSpec:
    """Block until the spec env var appears (reference:
    EstimatorExecutor.wait_for_tf_config)."""
    deadline = time.monotonic() + timeout_s
    while True:
        raw = os.environ.get(CLUSTER_SPEC_ENV)
        if raw:
            return ClusterSpec.from_json(raw)
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"no {CLUSTER_SPEC_ENV} after {timeout_s:.0f}s"
            )
        time.sleep(poll_s)


def synthesize_cluster_spec(
    client, task_type: str = "worker", task_index: Optional[int] = None
) -> ClusterSpec:
    """Build the spec from the live master: PS names come from
    ElasticPsService (get_ps_version, the authoritative ring), every
    OTHER role from the master's live node listing
    (get_running_nodes), and this process's identity from the client's
    node rank.  The reference synthesizes TF_CONFIG the same way from
    master-provided cluster info (new_tf_config, scheduler-side)."""
    resp = client.get_ps_version()
    idx = task_index
    if idx is None:
        idx = max(int(getattr(client, "node_rank", 0) or 0), 0)
    cluster: Dict[str, List[str]] = {}
    get_running = getattr(client, "get_running_nodes", None)
    if callable(get_running):
        try:
            for i, n in enumerate(get_running() or []):
                role = getattr(n, "type", "") or "worker"
                if role == "ps":
                    continue  # the versioned ring is authoritative
                # enumerate index as the id fallback: one node object
                # missing BOTH attrs must not raise and drop the whole
                # listing (the except below bails wholesale)
                name = (
                    getattr(n, "name", "")
                    or f"{role}-{getattr(n, 'id', i)}"
                )
                cluster.setdefault(role, []).append(name)
            for members in cluster.values():
                members.sort()
        except Exception as e:
            logger.warning("running-node listing failed: %s", e)
    cluster["ps"] = list(resp.servers)
    cluster.setdefault(task_type, [f"{task_type}-{idx}"])
    return ClusterSpec(
        cluster=cluster, task_type=task_type, task_index=idx
    )


# ---------------------------------------------------------------------------
# Run hooks (reference: tensorflow/hooks/*)
# ---------------------------------------------------------------------------


class SessionHook:
    """Per-step callbacks on the estimator loop (the SessionRunHook
    shape: begin / after_run / end)."""

    def begin(self, estimator):  # noqa: U100
        pass

    def after_run(self, estimator, step: int, loss):  # noqa: U100
        pass

    def end(self, estimator, step: int):  # noqa: U100
        pass


class GlobalStepReportHook(SessionHook):
    """Report the global step to the master each ``every_n`` steps
    (reference: hooks/global_step_hook.py + the training monitor's
    report path)."""

    def __init__(self, master_client, every_n: int = 10):
        self._client = master_client
        self._every = max(int(every_n), 1)

    def after_run(self, estimator, step, loss):
        if step % self._every == 0:
            try:
                self._client.report_global_step(step)
            except Exception as e:  # master restart must not kill training
                logger.warning("global-step report failed: %s", e)


class ElasticDataShardReportHook(SessionHook):
    """Report per-batch shard progress so the master can close shards
    and re-queue a dead worker's in-flight ones (reference:
    hooks/elastic_data_shard_report_hook.py — after_run calls
    report_batch_done)."""

    def __init__(
        self,
        shard_client,
        reader: Optional[FileReader] = None,
        batch_size: int = 1,
    ):
        self._client = shard_client
        self._reader = reader
        self._batch_size = int(batch_size)

    def after_run(self, estimator, step, loss):
        n = getattr(self._reader, "_last_batch_len", None)
        if n is None:
            n = (
                self._reader.batch_size
                if self._reader is not None
                else self._batch_size
            )
        try:
            self._client.report_batch_done(int(n))
        except Exception as e:
            logger.warning("shard report failed: %s", e)


class ModelInfoReportHook(SessionHook):
    """Report model statistics to the master once at train begin
    (reference: ReportModelInfoHook wired by the executor at
    estimator_executor.py:170) — the Brain's resource optimizer keys
    its plans off these job metrics."""

    def __init__(self, master_client, model_name: str = "",
                 num_params: int = 0, global_batch_size: int = 0):
        self._client = master_client
        self._model_name = model_name
        self._num_params = int(num_params)
        self._batch = int(global_batch_size)

    def begin(self, estimator):
        name = self._model_name or type(
            getattr(estimator, "_model", None) or estimator
        ).__name__
        try:
            self._client.report_model_info(
                model_name=name,
                num_params=self._num_params,
                global_batch_size=self._batch,
            )
        except Exception as e:
            logger.warning("model-info report failed: %s", e)


class CheckpointSaverHook(SessionHook):
    """Chief-only periodic checkpoint into ``model_dir/ckpt-{step}``
    with a tracker file and keep-max pruning (reference: the
    CheckpointSaverHook wired at estimator_executor.py:183-200).  With
    ``incremental_steps``, steps between full saves write delta-only
    snapshots into the latest full checkpoint's directory (cumulative —
    each overwrites the last)."""

    def __init__(self, estimator, save_steps: int,
                 incremental_steps: int = 0):
        self._est = estimator
        self._save_steps = max(int(save_steps), 1)
        self._incr = max(int(incremental_steps), 0)

    def after_run(self, estimator, step, loss):
        if step > 0 and step % self._save_steps == 0:
            estimator.save_checkpoint(step)
        elif self._incr and step > 0 and step % self._incr == 0:
            estimator.save_incremental(step)

    def end(self, estimator, step):
        # exceptional exits skip the end-of-run save: post-failure state
        # (e.g. a half-restored sparse tier) must not overwrite the last
        # good checkpoint, and a save error here must not mask the
        # original exception (ADVICE r5)
        if step > 0 and not getattr(estimator, "_train_failed", False):
            estimator.save_checkpoint(step)


# ---------------------------------------------------------------------------
# PS failover (reference: failover/tensorflow_failover.py + failover_client.py)
# ---------------------------------------------------------------------------


class PsFailureError(RuntimeError):
    """An unplanned PS loss was detected; the sparse tier needs a
    checkpoint restore (the reference's exit_from_recoverable_session
    path, tensorflow_failover.py:133)."""


class PsFailover:
    """Watch the master's PS cluster version and keep a
    DistributedEmbedding routed at the live server set.

    Change classification follows the reference
    (tensorflow_failover.py:91 ps_addresses_changed):

    - "scaling"   — the server count changed (planned scale-out/in):
      adopt live with bounded key migration, then ask the chief to
      checkpoint (info_cheif_do_checkpoints analog via ``on_change``).
    - "migrating" — same count, different members: same live adoption.
    - "ps_failure" — migration hit a dead server (its rows are gone):
      adopt the new ring WITHOUT migration and raise the restore path
      (``on_failure``; the estimator restores from the latest
      checkpoint).  The reference instead os._exit(2)s and lets the
      agent restart the worker — same recovery, more process churn.
    """

    def __init__(
        self,
        client,
        demb,
        poll_interval_s: float = 2.0,
        on_change: Optional[Callable[[str], None]] = None,
        on_failure: Optional[Callable[[], None]] = None,
    ):
        self._client = client
        self._demb = demb
        self._poll = poll_interval_s
        self._on_change = on_change
        self._on_failure = on_failure
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.changes: List[str] = []

    # one poll, callable inline from the training loop (the safe way:
    # re-routing must not race a concurrent pull/push on another thread)
    def poll_once(self) -> Optional[str]:
        from dlrover_tpu.sparse.server import resolve_ring, ring_weights

        resp = self._client.get_ps_version()
        if resp.version <= self._demb.version or not resp.servers:
            return None
        addrs = resolve_ring(self._client, list(resp.servers))
        if addrs is None:
            return None
        weights = ring_weights(self._client, resp)
        old = set(self._demb.server_names)
        new = set(resp.servers)
        change = "scaling" if len(old) != len(new) else "migrating"
        try:
            moved = self._demb.set_servers(addrs, weights=weights)
            self._demb.version = resp.version
            logger.info(
                "PS %s adopted live: %s → %s (%d keys migrated)",
                change, sorted(old), sorted(new), moved,
            )
        except OSError:
            # a source server is dead: its shard is unrecoverable from
            # the ring — adopt without migration and signal restore
            change = "ps_failure"
            self._demb.set_servers(addrs, weights=weights, migrate=False)
            self._demb.version = resp.version
            logger.warning(
                "PS failure: %s → %s without migration; sparse restore "
                "required", sorted(old), sorted(new),
            )
            if self._on_failure is not None:
                self._on_failure()
            self.changes.append(change)
            return change
        if self._on_change is not None:
            self._on_change(change)
        self.changes.append(change)
        return change

    def start(self):
        """Background polling — ONLY safe when no other thread is
        concurrently pulling/pushing through the DistributedEmbedding
        (set_servers swaps routing and closes clients mid-flight).  The
        Estimator therefore polls inline between steps instead; use
        this for idle-time watching (e.g. an evaluator waiting for a
        serving ring)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._poll):
                try:
                    self.poll_once()
                except Exception as e:
                    logger.warning("PS failover poll failed: %s", e)

        self._thread = threading.Thread(
            target=loop, name="ps-failover", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# Estimator (reference: EstimatorExecutor + tf.estimator.train_and_evaluate)
# ---------------------------------------------------------------------------


@dataclass
class RunConfig:
    """reference: estimator RunConfig fields the executor sets
    (estimator_executor.py:153-200); ``incremental_save_steps`` is the
    checkpoint_incremental_save_secs analog (estimator_executor.py:186
    — deeprec incremental saved-model), lowered onto the sparse tier's
    full-or-delta export: between full saves, only rows dirty since the
    last full (plus deletion tombstones) are written."""

    model_dir: str = "/tmp/dlrover_tpu_estimator"
    save_steps: int = 100
    keep_checkpoint_max: int = 5
    log_steps: int = 20
    # 0 = off; must divide into the save_steps cadence sensibly
    incremental_save_steps: int = 0
    # how long a train step's sparse wire error may wait for the master
    # to re-seal the PS ring before it propagates (the worker usually
    # notices a dead server BEFORE the master does)
    ps_failure_grace_s: float = 60.0


@dataclass
class TrainSpec:
    input_fn: Callable[[], Iterable]
    max_steps: int = 1000
    hooks: List[SessionHook] = field(default_factory=list)


@dataclass
class EvalSpec:
    input_fn: Callable[[], Iterable]
    steps: int = 16
    # evaluate every N train steps inside train_and_evaluate (the
    # reference throttles by seconds; steps are the deterministic
    # TPU-native cadence)
    every_steps: int = 200
    # metric the BestExporter compares; smaller is better for *loss
    metric: str = "loss"


class Estimator:
    """Estimator-shaped trainer over the sparse tier.

    ``model_fn(mode, params, cluster)`` → duck-typed model:
      - ``train_step(features, labels) -> loss`` (float or 0-dim array)
      - ``eval_metrics(features, labels) -> Dict[str, float]``
      - ``save(dir_path)`` / ``restore(dir_path)``
      - optional ``coll``: the DistributedEmbedding failover re-routes
      - optional ``predict(features)`` for ``predict()``
    """

    def __init__(
        self,
        model_fn: Callable[..., Any],
        config: Optional[RunConfig] = None,
        params: Optional[Dict] = None,
        cluster: Optional[ClusterSpec] = None,
        master_client=None,
        shard_client=None,
        reader: Optional[FileReader] = None,
    ):
        self.model_fn = model_fn
        self.config = config or RunConfig()
        self.params = dict(params or {})
        self.cluster = cluster or ClusterSpec()
        self.master_client = master_client
        self.shard_client = shard_client
        self.reader = reader
        self._model = None
        self.global_step = 0
        self.failover: Optional[PsFailover] = None
        self._needs_sparse_restore = False
        os.makedirs(self.config.model_dir, exist_ok=True)

    # -- model + failover wiring ------------------------------------------

    @property
    def model(self):
        if self._model is None:
            self._model = self.model_fn(
                ModeKeys.TRAIN, self.params, self.cluster
            )
            demb = getattr(self._model, "coll", None)
            if demb is not None and self.master_client is not None:
                self.failover = PsFailover(
                    self.master_client,
                    demb,
                    on_change=self._on_ps_change,
                    on_failure=self._on_ps_failure,
                )
        return self._model

    def _on_ps_change(self, change_type: str):
        # planned change: the ring already re-routed live; the chief
        # checkpoints so the new topology is durably restorable
        # (reference: info_cheif_do_checkpoints)
        if self.cluster.is_chief and self.global_step > 0:
            self.save_checkpoint(self.global_step)

    def _on_ps_failure(self):
        # unplanned loss: flag for the training loop — restore must not
        # race a step that is mid-pull on the monitor thread's watch
        self._needs_sparse_restore = True

    # -- checkpoints -------------------------------------------------------

    def _ckpt_dir(self, step: int) -> str:
        return os.path.join(self.config.model_dir, f"ckpt-{step}")

    def _tracker(self) -> str:
        return os.path.join(self.config.model_dir, "checkpoint")

    def _read_tracker(self) -> Optional[Dict]:
        try:
            with open(self._tracker(), "r", encoding="utf-8") as f:
                obj = json.loads(f.read())
            obj["latest_step"] = int(obj["latest_step"])
            obj["full_step"] = int(obj.get("full_step", obj["latest_step"]))
            return obj
        except (OSError, ValueError, KeyError):
            return None

    def latest_checkpoint(self) -> Optional[int]:
        """The step a restore resumes at (a delta step when incremental
        saves ran after the last full checkpoint)."""
        obj = self._read_tracker()
        return None if obj is None else obj["latest_step"]

    def _save_dataset_position(self, path: str):
        if self.shard_client is None:
            return
        try:
            pos = self.shard_client.checkpoint()
            with open(
                os.path.join(path, "dataset_position.json"),
                "w",
                encoding="utf-8",
            ) as f:
                f.write(pos or "{}")
        except Exception as e:
            logger.warning("dataset-position checkpoint failed: %s", e)

    def save_checkpoint(self, step: int):
        path = self._ckpt_dir(step)
        os.makedirs(path, exist_ok=True)
        self.model.save(path)
        self._save_dataset_position(path)
        with open(self._tracker(), "w", encoding="utf-8") as f:
            f.write(json.dumps({"latest_step": step, "full_step": step}))
        self._prune_checkpoints()
        logger.info("checkpoint saved at step %d → %s", step, path)

    def save_incremental(self, step: int):
        """Delta-only save into the latest full checkpoint's directory
        (sparse tier: rows dirty since that full + tombstones; dense
        params rewritten — they're small).  Cumulative, so each delta
        overwrites the previous one."""
        obj = self._read_tracker()
        if obj is None:
            # no full checkpoint yet to be incremental against
            self.save_checkpoint(step)
            return
        path = self._ckpt_dir(obj["full_step"])
        # capability probe by signature — catching TypeError around the
        # call itself would misread an internal save error as "no
        # delta support" and widen into a dir that still holds a stale
        # delta file
        import inspect

        try:
            supports_delta = (
                "delta_only"
                in inspect.signature(self.model.save).parameters
            )
        except (TypeError, ValueError):
            supports_delta = False
        if supports_delta:
            self.model.save(path, delta_only=True)
        else:
            logger.warning(
                "model.save has no delta_only parameter; incremental "
                "save at step %d falls back to a full checkpoint", step,
            )
            self.save_checkpoint(step)
            return
        self._save_dataset_position(path)
        with open(self._tracker(), "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"latest_step": step, "full_step": obj["full_step"]}
            ))
        logger.info(
            "incremental checkpoint at step %d → %s (full base %d)",
            step, path, obj["full_step"],
        )

    def _prune_checkpoints(self):
        keep = max(int(self.config.keep_checkpoint_max), 1)
        steps = sorted(
            int(d.split("-", 1)[1])
            for d in os.listdir(self.config.model_dir)
            if d.startswith("ckpt-") and d.split("-", 1)[1].isdigit()
        )
        for step in steps[:-keep]:
            import shutil

            shutil.rmtree(self._ckpt_dir(step), ignore_errors=True)

    def restore_latest(self) -> Optional[int]:
        obj = self._read_tracker()
        if obj is None:
            return None
        step = obj["latest_step"]
        # the directory is the last FULL save; the sparse restore
        # overlays its delta file, bringing state to ``step``
        path = self._ckpt_dir(obj["full_step"])
        self.model.restore(path)
        if self.shard_client is not None:
            # dataset position travels with the model state: a resumed
            # worker must not re-train shards consumed before step N
            pos_path = os.path.join(path, "dataset_position.json")
            try:
                with open(pos_path, "r", encoding="utf-8") as f:
                    self.shard_client.restore(f.read())
            except OSError:
                pass  # checkpoint predates position tracking
            except Exception as e:
                logger.warning("dataset-position restore failed: %s", e)
        logger.info("restored checkpoint step %d", step)
        return step

    # -- train / evaluate / predict ---------------------------------------

    def _default_hooks(self, extra: List[SessionHook]) -> List[SessionHook]:
        hooks: List[SessionHook] = list(extra)
        if self.cluster.is_chief:
            hooks.append(
                CheckpointSaverHook(
                    self,
                    self.config.save_steps,
                    self.config.incremental_save_steps,
                )
            )
        if self.shard_client is not None:
            if self.reader is not None and not self.reader.auto_report:
                hooks.append(
                    ElasticDataShardReportHook(
                        self.shard_client, self.reader
                    )
                )
            elif self.reader is None:
                # no reader to take batch sizes from: reporting one
                # record per step would never close a shard — the input
                # pipeline must report (FileReader(auto_report=True) or
                # an explicit ElasticDataShardReportHook(batch_size=N))
                logger.warning(
                    "shard_client set without a reader: shard "
                    "completion will NOT be auto-reported; use "
                    "FileReader(auto_report=True) or pass an explicit "
                    "ElasticDataShardReportHook"
                )
        if self.master_client is not None:
            hooks.append(GlobalStepReportHook(self.master_client))
            hooks.append(
                ModelInfoReportHook(
                    self.master_client,
                    model_name=type(self.model).__name__,
                    num_params=int(
                        getattr(self.model, "num_params", 0) or 0
                    ),
                )
            )
        return hooks

    def _await_reseal(self, err) -> bool:
        """After a sparse wire error, poll the master until the PS ring
        version moves (the failover path then adopts/flags a restore) or
        the grace window expires.  Returns True when a change was
        adopted — the caller re-enters the loop, which runs the restore
        if one was flagged.  Reference: the worker-exit-and-restart this
        replaces (tensorflow_failover.py:133 exits on ps_failure; here
        the worker rides through)."""
        logger.warning(
            "train step hit a sparse wire error (%s); waiting up to "
            "%.0fs for the master to re-seal the PS ring",
            err, self.config.ps_failure_grace_s,
        )
        deadline = time.monotonic() + self.config.ps_failure_grace_s
        while time.monotonic() < deadline:
            try:
                change = self.failover.poll_once()
            except Exception as e:  # master hiccup: keep waiting
                logger.warning("failover poll failed: %s", e)
                change = None
            if change is not None or self._needs_sparse_restore:
                return True
            time.sleep(min(self.failover._poll, 1.0))
        logger.error(
            "PS ring did not re-seal within %.0fs; propagating the "
            "wire error", self.config.ps_failure_grace_s,
        )
        return False

    def _maybe_poll_failover(self):
        """Inline failover poll between steps: re-routing on the calling
        thread can never race a pull/push in flight (the background
        PsFailover.start mode is for idle watchers only)."""
        if self.failover is None:
            return
        now = time.monotonic()
        if now - getattr(self, "_last_poll", 0.0) < self.failover._poll:
            return
        self._last_poll = now
        try:
            self.failover.poll_once()
        except Exception as e:
            logger.warning("PS failover poll failed: %s", e)

    def train(
        self,
        input_fn: Callable[[], Iterable],
        max_steps: int = 1000,
        hooks: Optional[List[SessionHook]] = None,
    ) -> float:
        model = self.model  # builds model + failover wiring
        all_hooks = self._default_hooks(list(hooks or []))
        for h in all_hooks:
            h.begin(self)
        last_loss = float("nan")
        self._last_poll = 0.0
        self._train_failed = False
        try:
            it = iter(input_fn())
            while self.global_step < max_steps:
                self._maybe_poll_failover()
                if self._needs_sparse_restore:
                    self._needs_sparse_restore = False
                    restored = self.restore_latest()
                    if restored is None:
                        raise PsFailureError(
                            "sparse tier lost a server and no checkpoint "
                            "exists to restore from"
                        )
                    # worker-restart step accounting: training resumes
                    # FROM the restored step — steps run since that
                    # checkpoint trained against sparse state that was
                    # just rolled back, so keeping their count would
                    # desync cadenced hooks from the actual state
                    self.global_step = int(restored)
                try:
                    features, labels = next(it)
                except StopIteration:
                    logger.info("input exhausted at step %d", self.global_step)
                    break
                try:
                    loss = model.train_step(features, labels)
                except OSError as e:
                    # sparse wire error: a PS died under this step. The
                    # worker sees it before the master does — wait for
                    # the master to re-seal the ring (version bump),
                    # adopt/restore through the normal failover path,
                    # and move on (this batch is dropped; its shard
                    # stays unreported, so the master re-queues it)
                    if self.failover is None or not self._await_reseal(e):
                        raise
                    continue
                last_loss = float(loss)
                self.global_step += 1
                for h in all_hooks:
                    h.after_run(self, self.global_step, last_loss)
                if self.global_step % self.config.log_steps == 0:
                    logger.info(
                        "step %d loss %.5f", self.global_step, last_loss
                    )
        except BaseException:
            self._train_failed = True
            raise
        finally:
            for h in all_hooks:
                try:
                    h.end(self, self.global_step)
                except Exception:
                    # on a failed run the original exception is the
                    # story; a hook's end error must not replace it
                    if not self._train_failed:
                        raise
                    logger.warning(
                        "hook %r end failed after training error",
                        h, exc_info=True,
                    )
        return last_loss

    def evaluate(
        self, input_fn: Callable[[], Iterable], steps: int = 16
    ) -> Dict[str, float]:
        model = self.model
        sums: Dict[str, float] = {}
        n = 0
        for features, labels in input_fn():
            # a PS change mid-eval must re-route here too, or the next
            # frozen pull hits a dead/stale server
            self._maybe_poll_failover()
            metrics = model.eval_metrics(features, labels)
            for k, v in metrics.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            n += 1
            if n >= steps:
                break
        return {k: v / max(n, 1) for k, v in sums.items()}

    def predict(self, input_fn: Callable[[], Iterable]) -> List[np.ndarray]:
        model = self.model
        out = []
        for features, _labels in input_fn():
            self._maybe_poll_failover()
            out.append(np.asarray(model.predict(features)))
        return out

    # -- best export (reference: BestExporter at estimator_executor.py:256)

    def export_best(self, metrics: Dict[str, float], metric: str) -> bool:
        """Keep ``model_dir/export/best`` at the checkpoint with the best
        (lowest) value of ``metric``.  Returns True when exported.

        The export is atomic: the model saves into a fresh temp dir
        under ``export/`` which then REPLACES ``best`` by rename, so a
        reader (or a second writer — chief and evaluator can both call
        this, see train_and_evaluate) never observes a half-written
        tree where ``metadata.json`` promises a model that isn't fully
        there."""
        export_root = os.path.join(self.config.model_dir, "export")
        export_dir = os.path.join(export_root, "best")
        meta_path = os.path.join(export_dir, "metadata.json")
        current = metrics.get(metric)
        if current is None:
            return False
        try:
            with open(meta_path, "r", encoding="utf-8") as f:
                best = float(json.loads(f.read())[metric])
        except (OSError, ValueError, KeyError):
            best = float("inf")
        if float(current) >= best:
            return False
        os.makedirs(export_root, exist_ok=True)
        tmp_dir = tempfile.mkdtemp(prefix=".best-", dir=export_root)
        # side-effect-free export when the model supports it: a plain
        # full save would clear the sparse tier's dirty epoch, silently
        # invalidating the chief's cumulative incremental checkpoints
        # (probe by signature like save_incremental does — a TypeError
        # from inside save must not be misread as "no support")
        import inspect

        try:
            supports_clear = (
                "clear_dirty"
                in inspect.signature(self.model.save).parameters
            )
        except (TypeError, ValueError):
            supports_clear = False
        try:
            if supports_clear:
                self.model.save(tmp_dir, clear_dirty=False)
            else:
                self.model.save(tmp_dir)
            with open(
                os.path.join(tmp_dir, "metadata.json"),
                "w",
                encoding="utf-8",
            ) as f:
                f.write(json.dumps({metric: float(current),
                                    "step": self.global_step}))
            # dir renames aren't atomic-replace like os.replace on a
            # file, so move the old tree aside first; a crash between
            # the two renames leaves NO best (plus a recoverable
            # .best-* temp) rather than a torn one
            old_dir = tmp_dir + ".old"
            if os.path.isdir(export_dir):
                os.rename(export_dir, old_dir)
            os.rename(tmp_dir, export_dir)
            shutil.rmtree(old_dir, ignore_errors=True)
        except Exception:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        logger.info(
            "best export updated: %s=%.5f at step %d",
            metric, float(current), self.global_step,
        )
        return True


def train_and_evaluate(
    estimator: Estimator, train_spec: TrainSpec, eval_spec: EvalSpec
) -> Dict[str, float]:
    """Interleave training and evaluation with best-export (reference:
    tf.estimator.train_and_evaluate as driven by
    EstimatorExecutor.train_and_evaluate, estimator_executor.py:274)."""
    metrics: Dict[str, float] = {}
    while estimator.global_step < train_spec.max_steps:
        target = min(
            estimator.global_step + eval_spec.every_steps,
            train_spec.max_steps,
        )
        before = estimator.global_step
        estimator.train(
            train_spec.input_fn, max_steps=target, hooks=train_spec.hooks
        )
        metrics = estimator.evaluate(
            eval_spec.input_fn, steps=eval_spec.steps
        )
        logger.info(
            "eval at step %d: %s", estimator.global_step, metrics
        )
        if estimator.cluster.is_chief and not estimator.cluster.cluster.get(
            "evaluator"
        ):
            # with a dedicated evaluator in the spec, run_evaluator owns
            # the best export — two writers would race the rename swap
            # and could pin "best" to whichever finished last, not the
            # better metric
            estimator.export_best(metrics, eval_spec.metric)
        if estimator.global_step == before:
            break  # input exhausted: stop instead of spinning
    return metrics


def run_evaluator(
    estimator: Estimator,
    eval_spec: EvalSpec,
    poll_interval_s: float = 10.0,
    stop_at_step: Optional[int] = None,
    allow_ring_restore: bool = False,
) -> Dict[str, float]:
    """The distributed EVALUATOR role (reference:
    tf.estimator.train_and_evaluate's evaluator task — a separate
    process that watches the model_dir, evaluates each new checkpoint,
    and keeps the best export; throttle_secs becomes
    ``poll_interval_s``).  Runs until ``stop_at_step``'s checkpoint has
    been evaluated (None = forever).  Sparse-tier models re-route
    through the failover poll inside evaluate(), so the evaluator
    survives PS membership changes like a trainer does.

    Refuses ring-backed models by default: ``restore_latest`` on a
    model whose embedding collection lives in the shared PS ring would
    PUSH stale checkpoint rows into the very tables the trainers are
    updating.  Build the evaluator's estimator with a local
    ``EmbeddingCollection`` (the snapshot formats interchange), or —
    when no trainer shares the ring, e.g. post-hoc evaluation after
    training stopped — pass ``allow_ring_restore=True``."""
    model = estimator.model
    coll = getattr(model, "coll", None)
    if coll is not None and not allow_ring_restore:
        from dlrover_tpu.sparse.server import DistributedEmbedding

        if isinstance(coll, DistributedEmbedding):
            raise ValueError(
                "run_evaluator on a ring-backed model would overwrite "
                "live PS rows on every checkpoint restore; give the "
                "evaluator its own model with a local "
                "EmbeddingCollection (checkpoints interchange between "
                "local and distributed collections), or pass "
                "allow_ring_restore=True if no trainer shares the ring"
            )
    last_evaled = None
    metrics: Dict[str, float] = {}
    while True:
        step = estimator.latest_checkpoint()
        if step is not None and step != last_evaled:
            estimator.restore_latest()
            estimator.global_step = step
            metrics = estimator.evaluate(
                eval_spec.input_fn, steps=eval_spec.steps
            )
            estimator.export_best(metrics, eval_spec.metric)
            last_evaled = step
            logger.info(
                "evaluator: checkpoint step %d → %s", step, metrics
            )
        if (
            stop_at_step is not None
            and last_evaled is not None
            and last_evaled >= stop_at_step
        ):
            return metrics
        time.sleep(poll_interval_s)


# ---------------------------------------------------------------------------
# Executor (reference: EstimatorExecutor.prepare + the launcher glue)
# ---------------------------------------------------------------------------


class EstimatorExecutor:
    """Wire an Estimator into a live job: cluster spec from env or
    synthesized from the master, shard-fed reader, failover monitor —
    then run train_and_evaluate (reference:
    estimator_executor.py:52-287)."""

    def __init__(
        self,
        model_fn,
        config: RunConfig,
        params: Optional[Dict] = None,
        master_client=None,
        shard_client=None,
        reader: Optional[FileReader] = None,
    ):
        raw = os.environ.get(CLUSTER_SPEC_ENV)
        if raw:
            cluster = ClusterSpec.from_json(raw)
        elif master_client is not None:
            cluster = synthesize_cluster_spec(master_client)
        else:
            cluster = ClusterSpec()
        self.estimator = Estimator(
            model_fn,
            config=config,
            params=params,
            cluster=cluster,
            master_client=master_client,
            shard_client=shard_client,
            reader=reader,
        )

    def train_and_evaluate(
        self, train_spec: TrainSpec, eval_spec: EvalSpec
    ) -> Dict[str, float]:
        # resume: a restarted worker picks up the latest checkpoint
        # (the reference reaches this via estimator model_dir recovery)
        restored = self.estimator.restore_latest()
        if restored is not None:
            self.estimator.global_step = restored
        return train_and_evaluate(self.estimator, train_spec, eval_spec)
