"""Single-chip training throughput benchmark.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: model FLOPs utilization (MFU) of a jitted train step on the largest
config that fits the local chip (lead attempt: llama-1.4b, whose dims all
tile the MXU exactly; gpt2-family fallbacks follow). The reference's
headline is Llama2-7B FSDP at 65.6% HFU on A100s (BASELINE.md #8);
``vs_baseline`` is our MFU / 0.656 — a hardware-neutral comparison of how
well each framework drives its accelerator.

Each candidate config runs in a subprocess with its own timeout, so a hung
compile or OOM on the big config cannot eat the whole bench budget.
"""

import json
import math
import os
import subprocess
import sys
import time

# bf16 peak TFLOP/s per chip by device kind
_PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
    "cpu": 0.1,  # placeholder so the bench still runs off-TPU
}

_REFERENCE_HFU = 0.656  # BASELINE.md #8

# one deadline for the whole run: attempts + aux passes must fit the
# documented `timeout 900 python bench.py` with slack for interpreter
# startup (the per-attempt budgets below must sum to <= this)
_DEADLINE_S = 870

# (config, batch, seq, remat, subprocess timeout seconds)
# llama-1.4b leads: every hot dim is a 128-multiple (d=16·128,
# head_dim=128, ff=44·128), measured ~10 MFU points over gpt2-1.5b's
# d=1600/head_dim=64 shapes on v5e — the MXU tiles cleanly.
# remat=save_qkv: fused CE (ops/fused_ce.py) freed the ~2 GiB f32
# logits working set, which buys pinning the qkv projections + flash
# residuals — backward skips ~30% of the full-remat recompute flops.
# Sequence length: b1·s8192 leads (same 8192 tokens/step as b8·s1024,
# so identical optimizer amortization and activation footprint) —
# longer sequences spend MORE of each token's flops in attention, which
# the Pallas flash kernel runs at MXU density, so utilization RISES
# with context length (measured r3, save_qkv: 0.626 b8·s1024 → 0.651
# b2·s4096 → 0.692 b1·s8192; 0.667 b1·s16384 save_attn). The
# reference's 65.6% HFU headline ran BLOCK_SIZE=4096
# (fsdp_llama2_entry.sh:11); the s4096 attempt is the seq-matched
# comparison and rides along as mfu_at_baseline_seq4096 in the
# emitted record.
# budgets sum to ≤870s so the documented `timeout 900 python bench.py`
# always reaches the tiny config even if every larger attempt grinds to
# its per-attempt timeout (CPU fall-through worst case)
_ATTEMPTS = [
    ("llama-1.4b", 1, 8192, "save_qkv", 280),
    ("llama-1.4b", 2, 4096, "save_qkv", 170),
    ("llama-1.4b", 8, 1024, "save_qkv", 110),
    # gpt2-1.5b's tied 50k-vocab embedding puts params at 1.56B, so
    # save_qkv's HBM-pinned residuals OOM the 16 GiB chip — but the
    # offload twin keeps the same residual set in pinned host memory,
    # escaping full remat's ~30% backward recompute; with d=64 the
    # attention kernels also run head-packed (attn_head_pack auto)
    ("gpt2-1.5b", 8, 1024, "save_qkv_offload", 110),
    ("gpt2-355m", 16, 1024, "full", 60),
    ("gpt2-124m", 16, 512, "none", 60),
    ("tiny", 8, 128, "none", 80),
]

# seq-matched companion for the long-context lead config (the baseline
# measured at 4096): embedded in the record when budget allows. Derived
# from the attempt ladder so the fallback record and the companion are
# always the SAME recipe.
_BASELINE_SEQ_COMPANION = _ATTEMPTS[1][:4]
assert _BASELINE_SEQ_COMPANION[2] == 4096

# the gpt2-family fallback stays MEASURED even when the flagship wins
# (BASELINE.md #8 is judged per shape family; without this the gpt2
# series would only appear in rounds where the flagship fails) —
# embedded as record["fallback"] when budget allows
_GPT2_FALLBACK = _ATTEMPTS[3][:4]
assert _GPT2_FALLBACK[0].startswith("gpt2")


# (n_head, head_dim) pairs the flash gate runs: the flagship's clean
# 128-wide heads AND the gpt2-family narrow-head shapes — gpt2-1.5b's
# odd 25 heads exercise auto head-packing (pack=2) plus the zero-pad
# path; gpt2-355m's even 16×64 packs without padding. The d<128
# entries double as the fp8 gate's shape source: the fp8 train path
# targets exactly this shape family (see _check_fp8_shape).
_KERNEL_CHECK_SHAPES = [(16, 128), (25, 64), (16, 64)]


def check_kernels(b=2, s=1024) -> bool:
    """On-chip numerics gate for the hand-written gradients in the hot
    path: the Pallas flash kernels (fwd+bwd vs mha_reference, at every
    _KERNEL_CHECK_SHAPES head geometry), the fused lm-head
    cross-entropy custom_vjp (vs the materialized-logits path), and the
    fp8 delayed-scaling GEMM (vs the plain dot, at the narrow-head
    family's projection shapes). Which gates run comes from the one
    capability table (accelerate.device_context.kernel_capabilities),
    the same gating the train step uses — so the bench checks exactly
    the kernel set that will execute.

    Runs at bench-like shapes on the REAL device (tests/test_ops.py and
    tests/test_fused_ce.py cover CPU/interpret mode only), so silent
    tile/clamp/chunk regressions show up in the BENCH json as
    kernels_ok=false instead of as quietly-wrong training.
    """
    import jax
    import numpy as np

    if jax.default_backend() == "cpu":
        return True  # the CPU fall-through path has no kernel to check

    from dlrover_tpu.accelerate.device_context import kernel_capabilities

    caps = kernel_capabilities()

    def close(a, b, tol):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        denom = np.maximum(np.abs(b).max(), 1e-6)
        return float(np.abs(a - b).max() / denom) < tol

    ok = True
    if caps.flash_attention:
        for h, d in _KERNEL_CHECK_SHAPES:
            ok = ok and _check_flash_shape(close, b, s, h, d)
    # paged decode kernel at the same head geometries: the serving path
    # gates on caps.paged_attention exactly like the engine does
    if caps.paged_attention:
        for h, d in _KERNEL_CHECK_SHAPES:
            ok = ok and _check_paged_shape(close, h, d)
    ok = ok and _check_fused_ce(close)
    # fp8 gate at the narrow-head family's GEMM shapes (d_model = h·d,
    # ff = 4·d_model — the gpt2 projections the fp8 path targets);
    # runs everywhere the bench runs on-device: non-native hardware
    # executes the same recipe through bf16 upcasts
    for h, d in _KERNEL_CHECK_SHAPES:
        if d < 128:
            ok = ok and _check_fp8_shape(
                close, h * d, 4 * h * d, caps.fp8_native
            )
    return bool(ok)


def _check_fp8_shape(close, k, n, native) -> bool:
    """fp8_dot (delayed scaling) vs the plain f32 GEMM at one (K, N):
    quantization noise only after the amax histories warm up, plus the
    state-on-cotangent convention (the backward's state output is a
    pushed amax history, not a gradient). On fp8-native hardware also
    pins native MXU dots against the bf16-upcast of the SAME quantized
    values — the documented everywhere-identical-numerics contract."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.ops import fp8

    kx, kw = jax.random.split(jax.random.key(23))
    x = jax.random.normal(kx, (256, k), jnp.bfloat16)
    w = jax.random.normal(kw, (k, n), jnp.bfloat16) * 0.02

    def loss(x, w, st):
        out = fp8.fp8_dot(x, w, st)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    # warm one step so the delayed scales reflect this data (the init
    # histories of ones would clip a unit-normal x)
    st = jax.jit(jax.grad(loss, argnums=2))(x, w, fp8.init_fp8_state())
    out = jax.jit(fp8.fp8_dot)(x, w, st)
    ref = jnp.dot(
        x.astype(jnp.float32), w.astype(jnp.float32)
    )
    ok = close(out, ref, 0.1)  # e4m3 quantization noise
    st2 = jax.jit(jax.grad(loss, argnums=2))(x, w, st)
    amax_x = float(jnp.max(jnp.abs(x.astype(jnp.float32))))
    ok = ok and abs(float(st2["amax_x"][-1]) - amax_x) < 1e-3 * amax_x
    ok = ok and st2["amax_g"].shape == st["amax_g"].shape
    if native:
        out_bf16 = jax.jit(
            lambda x, w, st: fp8.fp8_dot(x, w, st, native=False)
        )(x, w, st)
        ok = ok and close(out, out_bf16, 1e-2)
    return bool(ok)


def _check_flash_shape(close, b, s, h, d) -> bool:
    """Flash fwd+bwd vs mha_reference at one head geometry."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.ops.attention import mha_reference
    from dlrover_tpu.ops.pallas_attention import flash_attention

    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=True, block_q=1024,
                              block_k=1024)
        return jnp.sum(out.astype(jnp.float32) ** 2), out

    def loss_ref(q, k, v):
        out = mha_reference(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2), out

    (lf, of), gf = jax.jit(
        jax.value_and_grad(loss_flash, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    (lr_, orr), gr = jax.jit(
        jax.value_and_grad(loss_ref, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)

    ok = close(of, orr, 2e-2)
    for a, b_ in zip(gf, gr):
        ok = ok and close(a, b_, 3e-2)
    return bool(ok)


def _check_paged_shape(close, h, d, b=4, page_size=8, pages=6) -> bool:
    """Fused paged-decode kernel vs the pure-jnp block-table reference
    at one head geometry, on the REAL device: ragged per-slot lengths
    (pages partially filled, tables partially assigned), GQA when the
    head count allows it, decode (C=1) and chunk (C=4) variants, plus
    one sliding-window decode. The reference gathers only the pages the
    table names, so a kernel that walks one page too few/too many or
    mis-masks the tail shows up here as kernels_ok=false."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.ops import pallas_paged

    hkv = h // 4 if h % 4 == 0 else h  # GQA groups=4 when divisible
    n_phys = 1 + b * pages  # physical page 0 is the trash page
    ks = jax.random.split(jax.random.key(11), 4)
    pools = {
        "k": jax.random.normal(
            ks[0], (n_phys, page_size, hkv, d), jnp.bfloat16
        ),
        "v": jax.random.normal(
            ks[1], (n_phys, page_size, hkv, d), jnp.bfloat16
        ),
    }
    rng = np.random.default_rng(29)
    lens = rng.integers(page_size, pages * page_size, b)
    tables = np.full((b, pages), -1, np.int32)
    nxt = 1
    for i in range(b):
        for j in range(-(-int(lens[i]) // page_size)):
            tables[i, j] = nxt
            nxt += 1
    tables = jnp.asarray(tables)
    pos = jnp.asarray(lens - 1, jnp.int32)
    scale = d ** -0.5

    ok = True
    q1 = jax.random.normal(ks[2], (b, 1, h, d), jnp.bfloat16)
    for window in (0, 3 * page_size // 2):
        out_k = pallas_paged.paged_attention(
            q1, pools, tables, pos, scale=scale, window=window,
            kv_heads=hkv, variant="decode",
        )
        out_r = pallas_paged.paged_attention_reference(
            q1, pools, tables, pos, scale=scale, window=window,
            kv_heads=hkv, variant="decode",
        )
        ok = ok and close(out_k, out_r, 2e-2)
    c = 4
    qc = jax.random.normal(ks[3], (b, c, h, d), jnp.bfloat16)
    pos_c = pos[:, None] - jnp.arange(c - 1, -1, -1)[None, :]
    out_k = pallas_paged.paged_attention(
        qc, pools, tables, pos_c, scale=scale, kv_heads=hkv,
        variant="chunk",
    )
    out_r = pallas_paged.paged_attention_reference(
        qc, pools, tables, pos_c, scale=scale, kv_heads=hkv,
        variant="chunk",
    )
    ok = ok and close(out_k, out_r, 2e-2)
    return bool(ok)


def _check_fused_ce(close, b=2, s=512, dm=2048, v=32000) -> bool:
    """Fused CE vs materialized logits: logz + grads w.r.t. x and w."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.ops.fused_ce import fused_linear_ce

    kx, kw, kt = jax.random.split(jax.random.key(11), 3)
    x = jax.random.normal(kx, (b, s, dm), jnp.bfloat16)
    w = jax.random.normal(kw, (dm, v), jnp.bfloat16) * 0.02
    t = jax.random.randint(kt, (b, s), 0, v)

    def nll_fused(x, w):
        logz, tgt, _ = fused_linear_ce(x, w, t)
        return jnp.mean(logz - tgt)

    def nll_ref(x, w):
        logits = jnp.einsum(
            "bsd,dv->bsv", x, w, preferred_element_type=jnp.float32
        )
        logz = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t[..., None], -1)[..., 0]
        return jnp.mean(logz - tgt)

    lf, gf = jax.jit(jax.value_and_grad(nll_fused, argnums=(0, 1)))(x, w)
    lr, gr = jax.jit(jax.value_and_grad(nll_ref, argnums=(0, 1)))(x, w)
    ok = abs(float(lf) - float(lr)) / max(abs(float(lr)), 1e-6) < 1e-2
    for a, b_ in zip(gf, gr):
        ok = ok and close(a, b_, 3e-2)
    return bool(ok)


def measure_mxu_ceiling(n_pairs: int = 40, reps: int = 5) -> dict:
    """Achievable chained-matmul rate at the flagship's MLP shapes, plus
    the gpt2-1.5b fallback's shapes for comparison.

    The practical ceiling the step competes against — NOT the nominal
    peak. The second measurement quantifies the fallback config's
    documented shape penalty (d=1600 is 12.5 MXU tiles, so every matmul
    pads 1600 -> 1664): the bound the gpt2-1.5b MFU should be judged
    against rides in the BENCH json instead of only in the README.
    Methodology matters under the axon relay: a single timed call folds
    the ~100 ms host-readback into the measurement and reads 40-70%
    low; chaining ``reps`` calls and syncing once amortizes it.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    if jax.default_backend() == "cpu":
        # ~151 TFLOP of chained matmuls would grind past the subprocess
        # timeout on the CPU fall-through path, and the ratio against
        # the 0.1-TFLOPS placeholder peak is meaningless anyway
        return {}
    dev = jax.devices()[0]

    def chained_rate(n, d, f):
        a0 = jax.random.normal(jax.random.key(5), (n, d), jnp.bfloat16)
        wm = jax.random.normal(jax.random.key(6), (d, f), jnp.bfloat16)
        wm = wm * 0.02
        wn = jax.random.normal(jax.random.key(7), (f, d), jnp.bfloat16)
        wn = wn * 0.0005

        @jax.jit
        def chain(a):
            def body(c, _):
                c = jnp.dot(c, wm, preferred_element_type=jnp.bfloat16)
                c = jnp.dot(c, wn, preferred_element_type=jnp.bfloat16)
                return c, None

            out, _ = jax.lax.scan(body, a, None, length=n_pairs)
            return out

        out = chain(a0)
        float(jnp.sum(out.astype(jnp.float32)))  # warm + sync
        t0 = _time.perf_counter()
        for _ in range(reps):
            out = chain(out)
        float(jnp.sum(out.astype(jnp.float32)))
        dt = _time.perf_counter() - t0
        fl = 2 * n * d * f * 2 * n_pairs * reps
        return fl / dt / 1e12

    tf = chained_rate(8192, 2048, 5632)  # llama-1.4b MLP shapes
    tf_gpt2 = chained_rate(8192, 1600, 6400)  # gpt2-1.5b MLP shapes
    return {
        "mxu_tflops": round(tf, 1),
        "mxu_ceiling_frac": round(tf / peak_tflops(dev), 4),
        "mxu_ceiling_frac_gpt2_shapes": round(
            tf_gpt2 / peak_tflops(dev), 4
        ),
    }


def peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in _PEAK_TFLOPS.items():
        if key in kind:
            return val
    return 197.0


# bytes per element for the HLO shape dtypes that ride collectives
_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVE_OPS = (
    "reduce-scatter",
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
)


def collective_stats(hlo_text: str) -> dict:
    """Per-step collective profile of an optimized HLO module.

    Returns ``{"counts": {op: n}, "bytes_by_dtype": {dtype: B},
    "bytes_by_op": {op: B}}`` — op counts for each collective kind and
    the summed RESULT payload bytes grouped by wire dtype and by op.
    This is what the MULTICHIP dryrun embeds in its record so a
    replicated-update regression (full-gradient all-reduce sneaking
    back in) or a wire-dtype change is visible in the trajectory, not
    just in local tests. ``bytes_by_op`` feeds ``overlap_report``.
    """
    import re

    shape_re = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    counts = {op: 0 for op in _COLLECTIVE_OPS}
    bytes_by_dtype: dict = {}
    bytes_by_op: dict = {}
    for line in hlo_text.splitlines():
        parts = line.split(" = ", 1)
        if len(parts) != 2:
            continue
        rhs = parts[1]
        hit = None
        for op in _COLLECTIVE_OPS:
            k = rhs.find(op + "(")
            if k >= 0:
                hit = (op, k)
                break
        if hit is None:
            continue
        op, k = hit
        counts[op] += 1
        for dt, dims in shape_re.findall(rhs[:k]):
            if dt not in _HLO_DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            b = n * _HLO_DTYPE_BYTES[dt]
            bytes_by_dtype[dt] = bytes_by_dtype.get(dt, 0) + b
            bytes_by_op[op] = bytes_by_op.get(op, 0) + b
    return {
        "counts": {k: v for k, v in counts.items() if v},
        "bytes_by_dtype": bytes_by_dtype,
        "bytes_by_op": bytes_by_op,
    }


# Aggregate per-chip ICI bandwidth (GB/s) by device-kind substring —
# rough planning numbers for the overlap estimate, not spec-sheet
# precision; the report rounds to whole µs anyway. CPU gets a token
# value so virtual-device dryruns produce a structurally-valid report.
_ICI_GBPS = {
    "v4": 300.0,
    "v5 lite": 400.0,
    "v5e": 400.0,
    "v5p": 800.0,
    "v6 lite": 900.0,
    "v6e": 900.0,
    "v7": 1200.0,
    "cpu": 10.0,
}

# which step-phase window each collective class can hide under: the
# gradient wire (reduce-scatter / all-reduce / all-to-all) is issuable
# while the backward pass still computes earlier layers' grads; the
# param return (all-gather) overlaps the next forward. permute is
# pipeline traffic, on the critical path by construction — no window.
_BWD_OPS = ("reduce-scatter", "all-reduce", "all-to-all")
_FWD_OPS = ("all-gather",)

# bytes actually moved per chip, per RESULT byte, in a ring
# implementation at large dp: all-reduce moves ~2x its payload
# (reduce-scatter phase + all-gather phase), the others ~1x
_WIRE_FACTOR = {"all-reduce": 2.0}


def _ici_gbps(device_kind: str) -> float:
    kind = (device_kind or "").lower()
    for key, val in _ICI_GBPS.items():
        if key in kind:
            return val
    return 400.0


def overlap_report(stats, step_us, device_kind="", bwd_frac=2 / 3,
                   grad_accum=1, update_mode=""):
    """Exposed-vs-hidden time estimate for one step's collectives.

    For each collective class, wire time = payload bytes × ring factor
    / ICI bandwidth; the hiding window is the share of the step the
    scheduler can issue it under (backward ≈ ``bwd_frac`` of the step
    for gradient traffic, the rest for the all-gather param return;
    collective-permute gets no window — pipeline traffic is the
    critical path). Classes sharing a window compete for it, so
    exposure is computed per window and attributed to ops pro rata by
    their wire time. An ESTIMATE in the same counterfactual spirit as
    ``_nonmatmul_us_per_step``, not a profile: it exists so the bench
    record shows whether the ZeRO wire is latency we pay or latency
    we hide, and how that moves when bucket size / wire dtype change.

    ``update_mode="zero2"`` with ``grad_accum > 1`` scales the gradient
    wire (reduce-scatter / all-to-all) by ``grad_accum``: ZeRO-2 pays
    the exchange once per MICROBATCH (the scattered accumulator is what
    frees the full-grad buffer), and ``collective_stats`` counts the
    accum scan's body once. ZeRO-1 defers to one exchange per step, so
    its bytes pass through unscaled.

    Returns ``{"per_op": {op: {wire_us, window_us, exposed_us}},
    "exposed_us_total", "hidden_us_total", "assumed_ici_gbps"}``.
    """
    gbps = _ici_gbps(device_kind)
    by_op = dict(stats.get("bytes_by_op", {}))
    if update_mode == "zero2" and grad_accum > 1:
        for op in ("reduce-scatter", "all-to-all"):
            if op in by_op:
                by_op[op] = by_op[op] * grad_accum
    windows = {
        "bwd": step_us * bwd_frac,
        "fwd": step_us * (1 - bwd_frac),
        "none": 0.0,
    }
    wire = {}
    for op, b in by_op.items():
        wire[op] = b * _WIRE_FACTOR.get(op, 1.0) / (gbps * 1e3)
    per_op = {}
    exposed_total = 0.0
    hidden_total = 0.0
    for wname, ops in (
        ("bwd", _BWD_OPS),
        ("fwd", _FWD_OPS),
        ("none", ("collective-permute",)),
    ):
        w_total = sum(wire.get(op, 0.0) for op in ops)
        if w_total <= 0.0:
            continue
        win = windows[wname]
        exposed = max(0.0, w_total - win)
        for op in ops:
            if op not in wire:
                continue
            share = wire[op] / w_total
            per_op[op] = {
                "wire_us": round(wire[op], 1),
                "window_us": round(win, 1),
                "exposed_us": round(exposed * share, 1),
            }
        exposed_total += exposed
        hidden_total += w_total - exposed
    return {
        "per_op": per_op,
        "exposed_us_total": round(exposed_total, 1),
        "hidden_us_total": round(hidden_total, 1),
        "assumed_ici_gbps": gbps,
    }


def suggest_bucket_mb(total_grad_bytes, device_kind="", launch_us=5.0,
                      grad_accum=1, update_mode=""):
    """Bucket size for the ZeRO reduce-scatter wire, from the same
    bandwidth model as ``overlap_report``.

    Two constraints pull against each other: each bucket's wire time
    should dominate its launch latency (≥ ~4× ``launch_us``, else the
    exchange is launch-bound and fewer/bigger buckets win), and there
    should be ≥ 4 buckets so the first reduce-scatters issue while the
    backward tail still computes (one mega-bucket serializes the whole
    wire after the last gradient — see sharding.exchange_buckets'
    reverse issue order). Under ``update_mode="zero2"`` the exchange
    runs once per microbatch, so the launch cost recurs ``grad_accum``
    times per step against the SAME per-exchange payload — the
    launch-bound floor scales with ``grad_accum`` (bigger buckets,
    fewer total launches), while the ≥4-bucket cap still uses the
    per-microbatch bytes. Clamped to [1, 64] MB; the result is a
    starting point for ``CommConfig.bucket_mb``, not an oracle.
    """
    gbps = _ici_gbps(device_kind)
    passes = grad_accum if (update_mode == "zero2" and grad_accum > 1) else 1
    # smallest bucket whose wire time is >= 4x the per-step launch cost
    min_bytes = 4.0 * launch_us * passes * gbps * 1e3
    mb = max(1.0, min_bytes / 2**20)
    # but keep at least 4 buckets in flight per exchange
    mb = min(mb, max(1.0, total_grad_bytes / 4 / 2**20))
    return round(min(mb, 64.0), 2)


def drill_recovery_metric(path=None):
    """The latest eviction drill's ``recovery_s``, for the bench record.

    MFU says how fast training goes; ``recovery_s`` says how long a
    failure stops it. They are produced by different drivers into
    different artifacts (BENCH_*.json vs DRILL_*.json), so the bench
    record embeds the drill's number and the two trajectories share one
    comparable entry. Reads the drill artifact
    (``DLROVER_TPU_DRILL_ARTIFACT``, else the newest ``DRILL_r*.json``
    beside this file); returns ``None`` when no drill has run — the
    record then shows the metric as unmeasured rather than omitting it.
    """
    import glob

    if path is None:
        path = os.environ.get("DLROVER_TPU_DRILL_ARTIFACT")
    if path is None:
        here = os.path.dirname(os.path.abspath(__file__))
        candidates = sorted(glob.glob(os.path.join(here, "DRILL_r*.json")))
        path = candidates[-1] if candidates else None
    if not path:
        return None
    try:
        with open(path) as f:
            artifact = json.load(f)
    except (OSError, ValueError):
        return None
    failures = artifact.get("failures") or []
    if not failures:
        return None
    worst = max(
        (f for f in failures if "recovery_s" in f),
        key=lambda f: float(f["recovery_s"]),
        default=None,
    )
    if worst is None:
        return None
    out = {
        "recovery_s": float(worst["recovery_s"]),
        "kind": worst.get("kind", ""),
        "budget_s": artifact.get("recovery_budget_s"),
        "n_failures": len(failures),
    }
    evict = [
        f for f in failures
        if f.get("kind") == "host_eviction_live_reshard"
    ]
    if evict:
        out["live_reshard_recovery_s"] = float(evict[-1]["recovery_s"])
    return out


def serving_trajectory_metric(path=None):
    """The latest serving bench's headline numbers, for the train record.

    Same cross-artifact embed as ``drill_recovery_metric``: the serving
    bench writes ``SERVE_*.json`` (``bench.py serve`` with
    ``DLROVER_TPU_SERVE_ARTIFACT_OUT``); the train record carries its
    tokens/s-at-p99 so one trajectory file compares training AND serving
    across commits. Reads ``DLROVER_TPU_SERVE_ARTIFACT``, else the
    newest ``SERVE_*.json`` beside this file; None when serving has not
    been benched."""
    import glob

    if path is None:
        path = os.environ.get("DLROVER_TPU_SERVE_ARTIFACT")
    if path is None:
        here = os.path.dirname(os.path.abspath(__file__))
        candidates = sorted(glob.glob(os.path.join(here, "SERVE_*.json")))
        path = candidates[-1] if candidates else None
    if not path:
        return None
    try:
        with open(path) as f:
            artifact = json.load(f)
    except (OSError, ValueError):
        return None
    if artifact.get("serve_tokens_per_s") is None:
        return None
    out = {
        "serve_tokens_per_s": artifact["serve_tokens_per_s"],
        "serve_p99_ms": artifact.get("serve_p99_ms"),
        "p99_target_ms": artifact.get("p99_target_ms"),
        "p99_met": artifact.get("p99_met"),
    }
    # phase-latency axes (histogram-backed benches only — older
    # artifacts predate them, so project only when present)
    for key in (
        "ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms", "tpot_p99_ms",
        "queue_wait_p99_ms",
    ):
        if artifact.get(key) is not None:
            out[key] = artifact[key]
    spec = artifact.get("speculative")
    if spec:
        out["spec_tokens_per_s"] = spec.get("tokens_per_s")
        out["spec_accept_rate"] = spec.get("accept_rate")
        out["spec_speedup_vs_specoff"] = spec.get("speedup_vs_specoff")
    if artifact.get("migration_recovery_s") is not None:
        # serving-tier fault-tolerance headline: kill → first
        # post-migration token, plus the compute migrating saved over
        # the re-prefill failover it replaced
        out["migration_recovery_s"] = artifact["migration_recovery_s"]
        migr = artifact.get("migration") or {}
        out["migration_path"] = migr.get("path")
        out["migration_tokens_saved"] = migr.get(
            "tokens_saved_vs_reprefill"
        )
    pfx = artifact.get("prefix")
    if pfx:
        # prefix-sharing headline: how much of the hot-prefix trace the
        # radix index absorbed, and the one-copy memory win it bought
        out["prefix_hit_rate"] = pfx.get("prefix_hit_rate")
        out["prefill_tokens_saved"] = pfx.get("prefill_tokens_saved")
        out["resident_bytes_dedup_ratio"] = pfx.get(
            "resident_bytes_dedup_ratio"
        )
    asc = artifact.get("autoscale")
    if asc:
        # autoscaling headline: SLO goodput of the scaled fleet, the
        # breach→restored reaction time, and the decision count —
        # pre-autoscaler artifacts simply lack the block (replay via
        # absence, same pattern as the other feature sections)
        out["fleet_tokens_per_s_at_p99"] = asc.get(
            "fleet_tokens_per_s_at_p99"
        )
        out["autoscale_reaction_s"] = asc.get("autoscale_reaction_s")
        out["scale_decisions"] = asc.get("scale_decisions")
        out["autoscale_goodput_win"] = asc.get("goodput_win_vs_pinned1")
    dis = artifact.get("disagg")
    if dis:
        # disaggregation headline: how much the prefill/decode split
        # shields stream decode pace from a concurrent prompt burst
        # (>1 = split is better), plus the handoff tax it pays for it
        out["disagg_interference_win"] = dis.get(
            "tpot_p99_interference_win"
        )
        out["disagg_tpot_burst_p99_ms"] = (dis.get("disagg") or {}).get(
            "tpot_burst_p99_ms"
        )
        out["unified_tpot_burst_p99_ms"] = (
            dis.get("unified") or {}
        ).get("tpot_burst_p99_ms")
        out["disagg_handoff_ms_p99"] = (dis.get("disagg") or {}).get(
            "handoff_ms_p99"
        )
        out["disagg_tokens_per_s"] = (dis.get("disagg") or {}).get(
            "tokens_per_s"
        )
    return out


def sparse_serving_trajectory_metric(path=None):
    """The latest SPARSE serving bench's headline numbers, for the train
    record: QPS at fixed p99 with the tiered hit-rates.

    Same cross-artifact embed as ``serving_trajectory_metric``, but a
    separate artifact family (``SPARSE_SERVE_*.json``, written by
    ``bench.py sparse_serve`` with ``DLROVER_TPU_SPARSE_SERVE_ARTIFACT_OUT``)
    so old ``SERVE_*.json`` artifacts replay byte-for-byte unchanged.
    Reads ``DLROVER_TPU_SPARSE_SERVE_ARTIFACT``, else the newest
    ``SPARSE_SERVE_*.json`` beside this file; None when the sparse arm
    has not been benched."""
    import glob

    if path is None:
        path = os.environ.get("DLROVER_TPU_SPARSE_SERVE_ARTIFACT")
    if path is None:
        here = os.path.dirname(os.path.abspath(__file__))
        candidates = sorted(
            glob.glob(os.path.join(here, "SPARSE_SERVE_*.json"))
        )
        path = candidates[-1] if candidates else None
    if not path:
        return None
    try:
        with open(path) as f:
            artifact = json.load(f)
    except (OSError, ValueError):
        return None
    if artifact.get("sparse_qps") is None:
        return None
    out = {
        "sparse_qps": artifact["sparse_qps"],
        "sparse_p99_ms": artifact.get("sparse_p99_ms"),
        "sparse_p99_target_ms": artifact.get("sparse_p99_target_ms"),
        "sparse_p99_met": artifact.get("sparse_p99_met"),
        "sparse_prefetch_speedup": artifact.get(
            "sparse_prefetch_speedup"
        ),
        "sparse_outputs_exact_equal": artifact.get(
            "sparse_outputs_exact_equal"
        ),
    }
    tiers = (artifact.get("tiers") or {}).get("prefetch_on") or {}
    for key in ("hot_hit_rate", "prefetch_coverage",
                "promote_latency_avg_ms"):
        if tiers.get(key) is not None:
            out[f"sparse_{key}"] = tiers[key]
    return out


# fixed per-step host overhead fraction at the hand-tuned batch, for the
# CPU-side MFU model in the tuned arm: smaller planned batches run more
# (shorter) steps per token, so the fixed dispatch cost is a larger
# fraction of each. ~1% matches the measured host_dispatch_us_per_step
# share at the flagship shape.
_TUNED_DISPATCH_FRAC = 0.01

# the reference chip the cold-start plan is modeled against when the
# bench itself runs on CPU — the flagship _ATTEMPTS ladder was hand-tuned
# for a 16 GiB v5e, so that is the shape the planner must reproduce
_TUNED_REFERENCE_CHIP = "v5e"


def tuned_arm_metric(name, batch, seq, remat, device_kind=""):
    """The brain's cold-start plan vs this hand-tuned config, plus the
    live-refinement reaction time — the ``tuned`` arm of the record.

    Two numbers close the telemetry→config loop into the trajectory
    file:

    - ``cold_start_mfu_frac`` — modeled MFU of the zero-config plan as
      a fraction of the hand-tuned row's, CPU-modeled from the remat
      FLOP-expansion ladder (``_FLOP_EXPANSION``: recompute is executed
      MXU work MFU does not credit) and a fixed per-step dispatch
      overhead that scales inversely with batch. 1.0 when the planner
      reproduces the hand recipe exactly.
    - ``reaction_s`` — wall seconds for a ``BrainTuner`` fed a
      synthetic mid-run overlap-drift regression to emit a versioned
      revision (the changed knob rides along), measured in-process on
      the same plan.

    Never raises: a planner failure records ``{"error": ...}`` so the
    bench row survives a brain regression.
    """
    try:
        from dlrover_tpu.cluster import brain
        from dlrover_tpu.models import get_config

        cfg = get_config(
            name, max_seq=seq, remat=remat, param_dtype="bfloat16"
        )
        kind = device_kind if "TPU" in device_kind.upper() else ""
        kind = kind or _TUNED_REFERENCE_CHIP
        plan = brain.ColdStartPlanner().plan(
            cfg, n_devices=1, seq=seq, device_kind=kind
        )
        exp_hand = _FLOP_EXPANSION.get(remat, 1.0)
        exp_plan = _FLOP_EXPANSION.get(plan.remat or remat, 1.0)
        b_plan = plan.batch_size or batch
        o_hand = _TUNED_DISPATCH_FRAC
        o_plan = _TUNED_DISPATCH_FRAC * batch / max(1, b_plan)
        mfu_frac = (exp_hand * (1.0 + o_hand)) / (
            exp_plan * (1.0 + o_plan)
        )
        tuner = brain.BrainTuner(plan, cooldown_s=0.0)
        t0 = time.perf_counter()
        for _ in range(tuner._drift_patience):
            tuner.on_record(
                brain.telemetry.OverlapDriftRecord(
                    planned_exposed_us=100.0,
                    measured_collective_us=200.0,
                    drift_us=100.0,
                    drift_frac=1.0,
                )
            )
        reaction_s = time.perf_counter() - t0
        rev = tuner.revisions[-1] if tuner.revisions else None
        return {
            "planned": {
                "batch": b_plan,
                "remat": plan.remat or remat,
                "block_k": plan.block_k,
                "comm_bucket_mb": plan.comm_bucket_mb,
                "update_sharding": plan.update_sharding,
                "comm_wire_dtype": plan.comm_wire_dtype,
            },
            "hand": {"batch": batch, "remat": remat},
            "match": (plan.remat or remat) == remat
            and b_plan == batch,
            "cold_start_mfu_frac": round(mfu_frac, 4),
            "modeled_chip": kind,
            "reaction_s": round(reaction_s, 4),
            "reaction_knob": rev.knob if rev else "",
            "reaction_version": rev.version if rev else 0,
        }
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}


def _measure_migration(params, cfg, *, n_slots, max_len, page_size,
                       mode, prefill_chunk, seed):
    """Serving-tier recovery number: kill 1 of 2 replicas mid-decode
    and time from the kill to the FIRST post-migration token on the
    survivor (the serving analogue of the training drill's
    ``recovery_s``). Rides on the live KV-page migration path
    (serving/migration.py); ``tokens_saved_vs_reprefill`` is the
    prefill+decode compute the migration did NOT redo — the token
    savings of migrating over the old re-prefill failover. Returns
    None when the workload finished before a mid-stream kill landed."""
    import numpy as np

    from dlrover_tpu.serving.migration import ServingMigrator
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica

    kw = dict(
        n_slots=n_slots, max_len=max_len, page_size=page_size, mode=mode,
        prefill_chunk=prefill_chunk, idle_sleep=0.001,
    )
    max_new = max(8, min(16, max_len // 4))
    rng = np.random.default_rng(seed)
    alpha = min(9, cfg.vocab_size)
    prompts = [
        list(rng.integers(1, alpha, int(rng.integers(3, 10))))
        for _ in range(4)
    ]
    r0 = ServingReplica("bench-m0", params, cfg, node_id=0, **kw)
    r1 = ServingReplica("bench-m1", params, cfg, node_id=1, **kw)
    r0.start()
    r1.start()
    try:
        router = ReplicaRouter([r0, r1], migrator=ServingMigrator())

        def mid(rep, want):
            slots = [s for s in rep.server.engine.slots if s is not None]
            return len(slots) == want and all(
                s.phase == "decode" and s.generated
                and not s.req.future.done()
                for s in slots
            )

        # Park the victim's loop from the start and step its engine by
        # hand to a pinned mid-decode state — the warm decode rate is
        # far too fast to catch a mid-stream window by wall clock.
        t_kill = None
        gen_at_kill = {}
        with r1.server.paused() as eng:
            reqs = [router.submit(p, max_new) for p in prompts]
            # the survivor's own half finishes first (warming its jit)
            # so the recovery window times migration, not compilation
            for r in (reqs[0], reqs[2]):
                r.future.result(timeout=300)
            for _ in range(50):
                if mid(r1, 2):
                    break
                eng.step()
            if mid(r1, 2):
                gen_at_kill = {
                    s.req.rid: len(s.generated)
                    for s in eng.slots if s is not None
                }
                t_kill = time.perf_counter()
                r1.kill()
        if t_kill is None:
            return None
        deadline = time.monotonic() + 300
        router.poll()
        report = router.reports[-1]
        t_first = None
        while t_first is None and time.monotonic() < deadline:
            for s in list(r0.server.engine.slots):
                if (
                    s is not None
                    and s.req.rid in gen_at_kill
                    and len(s.generated) > gen_at_kill[s.req.rid]
                ):
                    t_first = time.perf_counter()
                    break
            else:
                if any(
                    r.future.done() for r in reqs if r.rid in gen_at_kill
                ):
                    t_first = time.perf_counter()
                else:
                    time.sleep(0.0005)
        router.wait_all(timeout=600)
        return {
            "migration_recovery_s": (
                round(t_first - t_kill, 4) if t_first else None
            ),
            "path": report.path,
            "migrated": len(report.placements),
            "re_prefilled": len(report.re_prefilled),
            "bytes_moved": report.bytes_moved,
            "tokens_saved_vs_reprefill": report.tokens_saved,
        }
    finally:
        r0.stop()
        r1.kill()


def _measure_hot_prefix(params, cfg, *, n_slots, max_len, page_size,
                        mode, prefill_chunk, seed, k_prompts=3,
                        n_requests=12, max_new=4):
    """Hot-prefix trace: a Zipf-ish mix of ``k_prompts`` shared system
    prompts × unique suffixes, run twice at the same seed — prefix
    sharing on vs off. The sharing-on arm should admit most requests
    through the radix index (prefix_hit_rate), skip the shared pages'
    prefill compute (prefill_tokens_saved, prefill-chunk reduction) and
    hold one physical copy of each hot prefix (resident dedup ratio);
    ``bitwise_equal_vs_sharing_off`` pins that the savings cost zero
    output fidelity. Donor requests (one per system prompt) are kept
    decoding through the trace so their pages stay referenced — the
    index drops a page the moment its last holder evicts."""
    import numpy as np

    from dlrover_tpu.serving.server import GenerationServer

    rng = np.random.default_rng(seed)
    alpha = min(9, cfg.vocab_size)
    sys_len = max_len // 2
    systems = [
        list(rng.integers(1, alpha, sys_len)) for _ in range(k_prompts)
    ]
    # Zipf-ish popularity: system prompt j drawn with p ∝ 1/(j+1)
    w = np.array([1.0 / (j + 1) for j in range(k_prompts)])
    picks = rng.choice(k_prompts, size=n_requests, p=w / w.sum())
    suffixes = [
        list(rng.integers(1, alpha, int(rng.integers(3, page_size + 3))))
        for _ in range(n_requests)
    ]
    # park each donor on a near-max budget and keep a few slots free
    # beyond them, so every donor outlives the whole trace — a donor
    # evicting mid-trace drops its pages from the index and turns the
    # rest of its followers into cold misses
    n_slots = max(n_slots, k_prompts + 3)
    donor_new = max_len - sys_len - 2

    def arm(sharing):
        srv = GenerationServer(
            params, cfg, replica=f"bench-px-{int(sharing)}",
            n_slots=n_slots, max_len=max_len, page_size=page_size,
            mode=mode, prefill_chunk=prefill_chunk,
            prefix_sharing=sharing, idle_sleep=0.001,
        ).start()
        try:
            eng = srv.engine
            srv.generate(list(np.arange(sys_len) % 4 + 1), 2,
                         timeout=600.0)  # eats both jit compiles
            eng._prefill_chunks = 0
            eng._prefix_hits = 0
            eng._prefix_misses = 0
            eng._prefill_tokens_saved = 0
            eng._cow_pages = 0
            eng._peak_dedup = 1.0
            base_prefill = eng.stats()["prefill_tokens"]
            donors = [
                srv.submit(s + [alpha + 1 + j], donor_new)
                for j, s in enumerate(systems)
            ]
            # wait until every donor's prompt is committed (and, with
            # sharing on, interned) before the trace lands — otherwise
            # the first wave of followers admits cold alongside them
            need = sum(sys_len + 1 for _ in systems)
            deadline = time.monotonic() + 300
            while (
                eng.stats()["prefill_tokens"] - base_prefill < need
                and time.monotonic() < deadline
            ):
                time.sleep(0.002)
            futs = [
                srv.submit(systems[p] + suffixes[i], max_new)
                for i, p in enumerate(picks)
            ]
            outs = [f.future.result(timeout=600.0) for f in futs]
            outs += [d.future.result(timeout=600.0) for d in donors]
            st = eng.stats()
        finally:
            srv.stop()
        return outs, st

    outs_on, st_on = arm(True)
    outs_off, st_off = arm(False)
    chunks_on = st_on["prefill_chunks"]
    chunks_off = st_off["prefill_chunks"]
    return {
        "k_prompts": k_prompts,
        "n_requests": n_requests,
        "prefix_hit_rate": round(st_on["prefix_hit_rate"], 4),
        "prefix_hits": st_on["prefix_hits"],
        "prefill_tokens_saved": st_on["prefill_tokens_saved"],
        "cow_pages": st_on["cow_pages"],
        "resident_bytes_dedup_ratio": round(
            st_on["peak_dedup_ratio"], 3
        ),
        "prefill_chunks_sharing_on": chunks_on,
        "prefill_chunks_sharing_off": chunks_off,
        "prefill_chunk_reduction": (
            round(chunks_off / chunks_on, 2) if chunks_on else None
        ),
        "bitwise_equal_vs_sharing_off": outs_on == outs_off,
    }


def _measure_disagg(params, cfg, *, n_slots, max_len, page_size, mode,
                    prefill_chunk, max_new, seed, n_streams=3, n_burst=6):
    """Prompt-burst interference: the same seeded trace served by one
    unified replica vs a 1-prefill + 1-decode split (serving/disagg.py).

    ``n_streams`` short-prompt requests reach steady decode first, then
    ``n_burst`` prompt-heavy requests land at once. On the unified
    engine every burst admission steals ``prefill_chunk``-token steps
    from the streams' decode cadence — their inter-token p99 inflates;
    on the split fleet the decode replica never runs a cold prefill, so
    the streams' pace holds while the prefill pool absorbs the burst.
    ``tpot_burst_p99_ms`` is measured over the STREAM requests only
    (the interference number); ``handoff_ms_p99`` is the decode
    replica's first-fragment→commit latency; fleet tokens/s and e2e
    p99 ride along. ``bitwise_equal_vs_unified`` pins that the split
    changed the transport schedule, not the numerics — both arms run
    the same ``prefill_chunk`` (chunk width changes reduction order)."""
    import numpy as np

    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica
    from dlrover_tpu.serving.scheduler import SamplingParams

    rng = np.random.default_rng(seed)
    alpha = min(9, cfg.vocab_size)
    stream_new = max(8, max_new)
    burst_len = max(prefill_chunk * 2, max_len // 2)
    stream_prompts = [
        list(rng.integers(1, alpha, 4)) for _ in range(n_streams)
    ]
    burst_prompts = [
        list(rng.integers(1, alpha, burst_len)) for _ in range(n_burst)
    ]
    sps = [
        SamplingParams(temperature=0.8, top_k=8, seed=31 + i)
        for i in range(n_streams + n_burst)
    ]
    kw = dict(
        n_slots=n_slots, max_len=max_len, page_size=page_size, mode=mode,
        prefill_chunk=prefill_chunk, idle_sleep=0.001,
    )

    def arm(roles):
        reps = [
            ServingReplica(
                f"bench-dg{i}-{role}", params, cfg, node_id=i,
                role=role, **kw,
            ).start()
            for i, role in enumerate(roles)
        ]
        router = ReplicaRouter(reps)
        try:
            # warmup ladder (same idea as one_pass): pays the prefill +
            # decode compiles at EVERY page-walk bucket a timed request
            # can reach, on every engine in the fleet — plus, on the
            # split arm, the staged-import path. A single warmup length
            # leaves bucket recompiles in the timed window, where they
            # stall the coordinator's paused() handshake for seconds.
            n_warm = 0
            for frac in (8, 4, 2, 1):
                warm_len = max(3, (max_len - 3) // frac - 2)
                router.submit(
                    list(np.arange(warm_len) % 4 + 1), 3
                )
                n_warm += 1
            router.wait_all(timeout=600.0)
            for r in reps:
                r.server.scheduler.reset_latencies()
            decode_eng = next(
                (r.server.engine for r in reps if r.role == "decode"),
                reps[0].server.engine,
            )
            t0 = time.perf_counter()
            streams = [
                router.submit(p, stream_new, sampling=sp)
                for p, sp in zip(stream_prompts, sps)
            ]
            # the burst lands only once every stream is PACING — decode
            # slots live, first token out — so the tpot window measures
            # interference, not prefill ordering
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                router.poll()
                pacing = sum(
                    1 for s in decode_eng.slots
                    if s is not None and s.phase == "decode" and s.generated
                )
                if pacing >= n_streams or all(
                    s.future.done() for s in streams
                ):
                    break
                time.sleep(0.002)
            burst = [
                router.submit(p, max_new, sampling=sp)
                for p, sp in zip(burst_prompts, sps[n_streams:])
            ]
            outs = router.wait_all(timeout=600.0)[n_warm:]  # drop warmup
            dt = time.perf_counter() - t0
            tpots = [
                (r.done_t - r.first_token_t) / (stream_new - 1) * 1e3
                for r in streams
                if r.first_token_t and r.done_t and stream_new >= 2
            ]
            hists = router.fleet_histograms()
            stats = [r.server.engine.stats() for r in reps]
            out = {
                "ttft_p50_ms": round(hists["ttft"].percentile(50.0), 2),
                "ttft_p99_ms": round(hists["ttft"].percentile(99.0), 2),
                "tpot_burst_p99_ms": round(
                    float(np.percentile(tpots, 99)), 2
                ) if tpots else None,
                "p99_ms": round(hists["e2e"].percentile(99.0), 2),
                "tokens_per_s": round(
                    (n_streams * stream_new + n_burst * max_new) / dt, 2
                ) if dt > 0 else 0.0,
            }
            if len(roles) > 1:
                out["handoffs"] = sum(s["handoffs_in"] for s in stats)
                out["handoff_bytes"] = sum(
                    s["handoff_bytes"] for s in stats
                )
                if "handoff" in hists and hists["handoff"].n:
                    out["handoff_ms_p99"] = round(
                        hists["handoff"].percentile(99.0), 2
                    )
            return outs, out
        finally:
            router.close()
            for r in reps:
                r.stop()

    outs_uni, uni = arm(["unified"])
    outs_dis, dis = arm(["prefill", "decode"])
    win = None
    if uni.get("tpot_burst_p99_ms") and dis.get("tpot_burst_p99_ms"):
        win = round(
            uni["tpot_burst_p99_ms"] / dis["tpot_burst_p99_ms"], 3
        )
    return {
        "n_streams": n_streams,
        "n_burst": n_burst,
        "burst_prompt_len": burst_len,
        "unified": uni,
        "disagg": dis,
        "tpot_p99_interference_win": win,
        "bitwise_equal_vs_unified": outs_uni == outs_dis,
    }


def _measure_autoscale(params, cfg, *, n_slots, max_len, page_size, mode,
                       prefill_chunk, max_new, seed, n_requests=16):
    """SLO-driven autoscaling headline: the same seeded hot-prefix
    burst trace served three ways — pinned to 1 replica, autoscaled
    1→2 (master/serving_autoscaler.py), and statically provisioned at
    2 (the bitwise reference). The metric is SLO GOODPUT: fleet
    tokens/sec counting only requests that finish inside the p99
    target (``fleet_tokens_per_s_at_p99``) — raw throughput at a blown
    tail is not serving capacity. The target is calibrated from the
    static-2 arm's measured p99 (×1.5 headroom) so the number tracks
    this host's speed instead of a wall-clock constant; the pinned-1
    arm blows it under the burst, the autoscaler's reaction decides
    how much of the trace the scaled fleet saves.

    ``autoscale_reaction_s`` is breach-edge → back-inside-SLO as the
    scaler itself measured it (the clear edge of its latched breach);
    ``scale_decisions`` counts actionable (out/in) decisions. Outputs
    are bitwise-compared across ALL arms: position-indexed sampling
    makes each request's tokens a function of (prompt, seed) only, so
    autoscaling may change WHERE a request runs, never what it says."""
    import numpy as np

    from dlrover_tpu.master.serving_autoscaler import (
        ServingAutoScaler, ServingScalerConfig,
    )
    from dlrover_tpu.serving.replica import ReplicaRouter, ServingReplica
    from dlrover_tpu.serving.scheduler import SamplingParams

    rng = np.random.default_rng(seed)
    alpha = min(9, cfg.vocab_size)
    sys_len = max(prefill_chunk, min(prefill_chunk * 2, max_len // 3))
    systems = [list(rng.integers(1, alpha, sys_len)) for _ in range(2)]
    prompts = [
        systems[i % 2] + list(rng.integers(1, alpha, 4))
        for i in range(n_requests)
    ]
    sps = [
        SamplingParams(temperature=0.8, top_k=8, seed=71 + i)
        for i in range(n_requests)
    ]
    kw = dict(
        n_slots=n_slots, max_len=max_len, page_size=page_size, mode=mode,
        prefill_chunk=prefill_chunk, idle_sleep=0.001,
        # pace every replica like a fixed-rate accelerator host (see
        # GenerationServer.step_period_s): co-located engine loops
        # share this machine's cores, so without pacing a second
        # "replica" adds contention instead of capacity and the whole
        # pinned-vs-scaled comparison inverts
        step_period_s=0.02,
    )

    def arm(n_start, autoscale, target_ms):
        reps = [
            ServingReplica(
                f"bench-as{i}", params, cfg, node_id=i, **kw
            ).start()
            for i in range(n_start)
        ]
        router = ReplicaRouter(reps)
        spare = None
        scaler = None
        try:
            # warmup ladder (same rationale as _measure_disagg): pays
            # every page-walk bucket's compiles before the timed window
            n_warm = 0
            for frac in (8, 4, 2, 1):
                warm_len = max(3, (max_len - 3) // frac - 2)
                router.submit(list(np.arange(warm_len) % 4 + 1), 3)
                n_warm += 1
            router.wait_all(timeout=600.0)
            # the sampled-decode path is a separate per-instance jit
            # wrapper: warm it on EVERY replica or the first timed
            # request pays seconds of compile inside the window
            for r in reps:
                r.server.generate(
                    list(np.arange(prefill_chunk) % 4 + 1), 3,
                    sampling=SamplingParams(
                        temperature=0.8, top_k=8, seed=7
                    ),
                    timeout=600.0,
                )
            if autoscale:
                # the warm spare the provision_fn hands out: started
                # AND ladder-warmed — the engine's jit wrappers are
                # per-instance, so an unwarmed joiner would pay its
                # compiles inside the timed window and a "scale-out"
                # would slow the fleet down
                spare = ServingReplica(
                    "bench-as-spare", params, cfg, node_id=9, **kw
                ).start()
                for frac in (8, 4, 2, 1):
                    warm_len = max(3, (max_len - 3) // frac - 2)
                    spare.server.generate(
                        list(np.arange(warm_len) % 4 + 1), 3,
                        timeout=600.0,
                    )
                spare.server.generate(
                    list(np.arange(prefill_chunk) % 4 + 1), 3,
                    sampling=SamplingParams(
                        temperature=0.8, top_k=8, seed=7
                    ),
                    timeout=600.0,
                )
                spare.server.scheduler.reset_latencies()
                scaler = ServingAutoScaler(
                    router,
                    ServingScalerConfig(
                        p99_target_ms=target_ms,
                        queue_depth_high=n_slots,
                        cooldown_s=1.0,
                        min_replicas=1,
                        max_replicas=2,
                        min_window_n=4,
                        # never shrink inside the bench window — the
                        # scale-in story is the drill's, not this arm's
                        shrink_after_clear=10**6,
                        interval_s=0.02,
                    ),
                    provision_fn=lambda role: spare,
                ).start()
            for r in reps:
                r.server.scheduler.reset_latencies()
            t0 = time.perf_counter()
            reqs = [
                router.submit(p, max_new, sampling=sp)
                for p, sp in zip(prompts, sps)
            ]
            outs = router.wait_all(timeout=600.0)[n_warm:]
            dt = time.perf_counter() - t0
            lats_ms = [
                (r.done_t - r.submit_t) * 1e3 for r in reqs if r.done_t
            ]
            out = {
                "n_replicas_start": n_start,
                "tokens_per_s": round(n_requests * max_new / dt, 2)
                if dt > 0 else 0.0,
                "p99_ms": round(
                    float(np.percentile(lats_ms, 99)), 2
                ) if lats_ms else None,
                "n_requests": n_requests,
                "_lats_ms": lats_ms,
                "_dt": dt,
            }
            if scaler is not None:
                # idle ticks after the trace let the latched breach
                # clear so the restore edge (reaction) is recorded
                deadline = time.monotonic() + 5.0
                while (
                    time.monotonic() < deadline
                    and scaler.last_restore_s <= 0.0
                ):
                    time.sleep(0.02)
                scaler.stop()
                out["scale_decisions"] = sum(
                    1 for d in scaler.decisions if d.direction
                )
                out["autoscale_reaction_s"] = round(
                    scaler.last_restore_s, 3
                ) if scaler.last_restore_s > 0 else None
                out["decision_reaction_s"] = round(
                    scaler.last_reaction_s, 3
                )
                out["n_replicas_final"] = len(router.live_replicas())
            return outs, out
        finally:
            if scaler is not None:
                scaler.stop()
            router.close()
            for r in reps + ([spare] if spare is not None else []):
                r.stop()

    # static-2 first: the bitwise reference AND the target calibration
    outs_static, static2 = arm(2, False, float("inf"))
    target_ms = max(1.0, (static2["p99_ms"] or 1.0) * 1.3)
    outs_pin, pinned1 = arm(1, False, target_ms)
    outs_auto, autoscaled = arm(1, True, target_ms)
    # goodput accounting against the calibrated target, uniformly for
    # every arm (the raw per-request latencies travel out of arm())
    for info in (static2, pinned1, autoscaled):
        lats, dt = info.pop("_lats_ms"), info.pop("_dt")
        within = sum(1 for l in lats if l <= target_ms)
        info["within_target"] = within
        info["goodput_tokens_per_s"] = round(
            within * max_new / dt, 2
        ) if dt > 0 else 0.0
    win = None
    if pinned1["goodput_tokens_per_s"]:
        win = round(
            (autoscaled["goodput_tokens_per_s"] or 0.0)
            / pinned1["goodput_tokens_per_s"], 3,
        )
    return {
        "p99_target_ms": round(target_ms, 2),
        "pinned1": pinned1,
        "autoscaled": autoscaled,
        "static2": static2,
        "fleet_tokens_per_s_at_p99": autoscaled["goodput_tokens_per_s"],
        "autoscale_reaction_s": autoscaled.get("autoscale_reaction_s"),
        "scale_decisions": autoscaled.get("scale_decisions", 0),
        "goodput_win_vs_pinned1": win,
        "bitwise_equal_vs_static2": outs_auto == outs_static,
        "bitwise_equal_pinned_vs_static2": outs_pin == outs_static,
    }


def run_serve(name="tiny", n_requests=8, mode="int8", n_slots=4,
              max_len=64, page_size=8, prefill_chunk=8, max_new=8,
              p99_target_ms=60000.0, seed=0, paged=True,
              compare_gather=True, spec_k=3, compare_spec=True,
              measure_migration=True, measure_prefix=True,
              measure_disagg=True, measure_autoscale=True):
    """Serving throughput: tokens/sec at a fixed p99 latency target.

    Drives the continuous-batching engine (dlrover_tpu/serving/) with
    ``n_requests`` mixed-length concurrent requests through the threaded
    server, after one warmup request that eats both jit compiles
    (prefill chunk + decode batch). The headline is decode tokens/sec
    over the timed window, REPORTED AGAINST the p99 end-to-end latency —
    throughput is only comparable across commits at a fixed tail-latency
    budget, so ``p99_met`` rides along and a p99 regression shows up
    even when tokens/s improves. Also records the paged-KV memory story:
    int8+scales resident bytes vs the bf16 reference geometry (the
    ≥1.7× reduction the serving docs quote).

    Paged-decode evidence (docs/performance.md): ``decode_kernel`` says
    which attention path ran; ``hbm_traffic_model`` is the analytic
    bytes-touched-per-decode-token model at this geometry (paged ≈ pages
    actually held, gather ≈ the full S_max pool; see
    kv_cache.decode_traffic_bytes); ``phase_split`` divides wall time
    into jitted step vs host scheduling (plus how often the block table
    was re-shipped — the dirty-flag counter). With ``compare_gather``
    a second identically-seeded pass runs the legacy gather engine and
    ``paged_speedup_vs_gather`` records the measured ratio.

    With ``compare_spec`` a speculative-decoding arm
    (``spec_k`` prompt-lookup drafts per slot per step) reruns the
    SAME seeded workload and records its tokens/s-at-p99 plus the
    measured acceptance rate under ``"speculative"``. The prompts
    draw from a small alphabet so n-gram lookup has something to
    match — acceptance on random-token prompts would be ~0 and the
    arm would measure only verify overhead. ``speedup_vs_specoff``
    is reported as measured: on CPU the batched verify step often
    does NOT beat plain decode (the crossover needs accelerator
    batch economics), and the artifact says so honestly.

    With ``measure_prefix`` a hot-prefix trace (Zipf-ish mix of shared
    system prompts × unique suffixes) runs twice at the same seed —
    prefix sharing on vs off — and records the hit rate, the prefill
    compute the radix index absorbed, the resident dedup ratio, and a
    bitwise-equality flag under ``"prefix"``.

    With ``measure_disagg`` the same seeded trace runs unified vs a
    1-prefill + 1-decode split under a concurrent prompt burst and
    records the stream-decode interference number (tpot p99), handoff
    latency/bytes, and a bitwise flag under ``"disagg"``.

    With ``measure_autoscale`` a seeded hot-prefix burst runs pinned-1
    vs autoscaled-1→2 vs static-2 and records the SLO-goodput win,
    ``autoscale_reaction_s``, the decision count, and a bitwise flag
    under ``"autoscale"`` (headlines mirrored at top level)."""
    import numpy as np

    import jax

    from dlrover_tpu.models import decoder, get_config
    from dlrover_tpu.serving import kv_cache as kvc
    from dlrover_tpu.serving.server import GenerationServer

    cfg = get_config(
        name, n_layer=2, d_model=64, d_ff=128, n_head=4,
        vocab_size=128, max_seq=max_len,
    ) if name == "tiny" else get_config(name, max_seq=max_len)
    params = decoder.init(jax.random.key(seed), cfg)

    def one_pass(use_paged, bucketing=True, use_spec_k=0):
        srv = GenerationServer(
            params, cfg, replica="bench", n_slots=n_slots,
            max_len=max_len, page_size=page_size, mode=mode,
            prefill_chunk=prefill_chunk, paged=use_paged,
            page_bucketing=bucketing, spec_k=use_spec_k,
        ).start()
        try:
            # warmup: pays the prefill-chunk + decode-batch compiles.
            # A ladder of prompt lengths (…, half, near-max) runs both
            # jitted steps at every page-walk bucket a timed request
            # can reach, so bucket recompiles land here, not in the
            # timed window. With speculation on, an always-propose
            # draft is installed FOR THE WARMUP ONLY: prompt-lookup
            # over the warmup's (untrained-model) generated tokens can
            # fail to match, silently fall back to plain decode, and
            # leak the verify-step compile — one or more seconds per
            # page bucket — into the timed window. Forcing proposals
            # guarantees the verify jit compiles at every bucket the
            # ladder reaches; the real proposer is restored before
            # timing, so the measured accept rate is the real one.
            warm_new = 2 + (use_spec_k + 1 if use_spec_k else 0)
            real_draft = srv.engine.draft
            if use_spec_k:
                class _WarmDraft:
                    def propose(self, history, k):
                        return [int(history[-1])] * k

                srv.engine.draft = _WarmDraft()
            for frac in (8, 4, 2, 1):
                warm_len = max(3, (max_len - warm_new) // frac - 2)
                warm = list(np.arange(warm_len) % 4 + 1)
                srv.generate(warm, warm_new, timeout=600.0)
            srv.engine.draft = real_draft
            srv.scheduler.reset_latencies()
            srv.engine._tokens = 0
            srv.engine._t0 = None
            srv.engine._step_time = 0.0
            srv.engine._draft_tokens = 0
            srv.engine._accepted_tokens = 0

            rng = np.random.default_rng(seed)
            lens = rng.integers(
                2, max(3, max_len - max_new - 1), n_requests
            )
            # small-alphabet prompts: every arm shares them, and the
            # repetition gives the spec arm's n-gram lookup real
            # structure to match (see docstring)
            alpha = min(9, cfg.vocab_size)
            t0 = time.perf_counter()
            futs = [
                srv.submit(
                    list(rng.integers(1, alpha, int(n))),
                    max_new,
                ).future
                for n in lens
            ]
            for f in futs:
                f.result(timeout=600.0)
            dt = time.perf_counter() - t0
            lat = srv.scheduler.latency_summary()
            stats = srv.engine.stats()
            geom = srv.engine.geom
        finally:
            srv.stop()
        tps = n_requests * max_new / dt if dt > 0 else 0.0
        return tps, dt, lat, stats, geom, lens

    tokens_per_s, dt, lat, eng_stats, geom, lens = one_pass(paged)

    bf16_geom = geom._replace(mode="bf16")
    b_int8 = kvc.resident_bytes(geom._replace(mode="int8"))
    b_bf16 = kvc.resident_bytes(bf16_geom)
    # analytic HBM model at this run's steady state: every slot busy,
    # holding the pages for an average-length finished request
    avg_total = float(np.mean(lens)) + max_new
    pages_held = n_slots * math.ceil(avg_total / page_size)
    paged_step = kvc.decode_traffic_bytes(geom, pages_held, n_slots, True)
    gather_step = kvc.decode_traffic_bytes(
        geom, pages_held, n_slots, False
    )
    record = {
        "metric": f"serve_tokens_per_s[{cfg.name},{mode},{n_slots}slots]",
        "value": round(tokens_per_s, 2),
        "unit": "new_tokens_per_sec",
        "serve_tokens_per_s": round(tokens_per_s, 2),
        "serve_p50_ms": round(lat["p50"], 2),
        "serve_p99_ms": round(lat["p99"], 2),
        "p99_target_ms": p99_target_ms,
        "p99_met": lat["p99"] <= p99_target_ms,
        # per-phase latency from the scheduler's log-bucketed
        # histograms (observability/histogram.py) — TTFT/TPOT are the
        # interactive-serving SLO axes e2e alone can't resolve
        "ttft_p50_ms": round(lat["ttft_p50_ms"], 2),
        "ttft_p99_ms": round(lat["ttft_p99_ms"], 2),
        "tpot_p50_ms": round(lat["tpot_p50_ms"], 2),
        "tpot_p99_ms": round(lat["tpot_p99_ms"], 2),
        "queue_wait_p99_ms": round(lat["queue_wait_p99_ms"], 2),
        "n_requests": n_requests,
        "max_new_tokens": max_new,
        "decode_kernel": eng_stats["decode_kernel"],
        "phase_split": {
            "wall_s": round(dt, 4),
            "step_time_s": round(eng_stats["step_time_s"], 4),
            "host_time_s": round(eng_stats["host_time_s"], 4),
            "table_ships": eng_stats["table_ships"],
        },
        "hbm_traffic_model": {
            "pages_held": pages_held,
            "paged_bytes_per_token": paged_step // n_slots,
            "gather_bytes_per_token": gather_step // n_slots,
            "model_reduction": round(gather_step / paged_step, 2),
        },
        "kv_cache": {
            "mode": mode,
            "page_size": page_size,
            "resident_bytes": kvc.resident_bytes(geom),
            "resident_bytes_int8": b_int8,
            "resident_bytes_bf16": b_bf16,
            "reduction_vs_bf16": round(b_bf16 / b_int8, 3),
        },
    }
    if compare_gather and paged:
        # two baselines: the post-PR gather fallback (pages-held
        # bucketed width) and the pre-PR engine it replaced (full
        # S_max-wide gather+scatter every step)
        g_tps = one_pass(False)[0]
        legacy_tps = one_pass(False, bucketing=False)[0]
        record["gather_tokens_per_s"] = round(g_tps, 2)
        record["legacy_gather_tokens_per_s"] = round(legacy_tps, 2)
        record["paged_speedup_vs_gather"] = (
            round(tokens_per_s / g_tps, 3) if g_tps > 0 else None
        )
        record["paged_speedup_vs_legacy"] = (
            round(tokens_per_s / legacy_tps, 3) if legacy_tps > 0
            else None
        )
    if compare_spec and spec_k:
        s_tps, _, s_lat, s_stats, _, _ = one_pass(
            paged, use_spec_k=spec_k
        )
        record["speculative"] = {
            "spec_k": spec_k,
            "tokens_per_s": round(s_tps, 2),
            "p99_ms": round(s_lat["p99"], 2),
            "p99_met": s_lat["p99"] <= p99_target_ms,
            "draft_tokens": s_stats["draft_tokens"],
            "accepted_tokens": s_stats["accepted_tokens"],
            "accept_rate": round(s_stats["spec_accept_rate"], 4),
            "speedup_vs_specoff": (
                round(s_tps / tokens_per_s, 3)
                if tokens_per_s > 0 else None
            ),
        }
    if measure_migration:
        migr = _measure_migration(
            params, cfg, n_slots=n_slots, max_len=max_len,
            page_size=page_size, mode=mode, prefill_chunk=prefill_chunk,
            seed=seed,
        )
        record["migration"] = migr
        record["migration_recovery_s"] = (
            migr.get("migration_recovery_s") if migr else None
        )
    if measure_prefix:
        record["prefix"] = _measure_hot_prefix(
            params, cfg, n_slots=n_slots, max_len=max_len,
            page_size=page_size, mode=mode, prefill_chunk=prefill_chunk,
            seed=seed,
        )
    if measure_disagg:
        record["disagg"] = _measure_disagg(
            params, cfg, n_slots=n_slots, max_len=max_len,
            page_size=page_size, mode=mode, prefill_chunk=prefill_chunk,
            max_new=max_new, seed=seed,
        )
    if measure_autoscale:
        asc = _measure_autoscale(
            params, cfg, n_slots=n_slots, max_len=max_len,
            page_size=page_size, mode=mode, prefill_chunk=prefill_chunk,
            max_new=max_new, seed=seed,
        )
        record["autoscale"] = asc
        # headline pair: SLO goodput of the scaled fleet + how fast the
        # control loop got the tail back inside the target
        record["fleet_tokens_per_s_at_p99"] = asc[
            "fleet_tokens_per_s_at_p99"
        ]
        record["autoscale_reaction_s"] = asc["autoscale_reaction_s"]
    return record


class _CalibratedColdStore:
    """Cold tier with a calibrated per-multi-get stall, modelling a
    seek-dominated disk / remote store: every batched ``get`` pays one
    fixed latency regardless of batch size (that amortization is
    exactly what the lookahead prefetcher buys). Writes pass through
    unstalled — demotion is off the request path either way."""

    def __init__(self, inner, get_latency_s):
        self.inner = inner
        self.get_latency_s = float(get_latency_s)
        self.width = inner.width

    def get(self, keys):
        if len(keys):
            time.sleep(self.get_latency_s)
        return self.inner.get(keys)

    def put(self, keys, rows, freqs, timestamps):
        self.inner.put(keys, rows, freqs, timestamps)

    def delete(self, keys):
        self.inner.delete(keys)

    def flush(self):
        self.inner.flush()

    def close(self):
        self.inner.close()

    def __len__(self):
        return len(self.inner)


def run_sparse_serve(n_requests=160, n_fields=8, n_dense=6, emb_dim=16,
                     id_space=5000, cold_get_latency_ms=8.0,
                     p99_target_ms=10000.0, seed=0,
                     prefetch_lookahead=16):
    """Tiered sparse-embedding serving: request QPS at a fixed p99.

    The recommender scenario (docs/sparse_serving.md): a DeepFM replica
    scores single requests (``max_batch=1`` — the online-serving
    arrival model where each request has its own latency budget) whose
    embedding rows start ENTIRELY in the cold tier behind a calibrated
    per-multi-get stall. The same seeded trace runs twice — lookahead
    prefetch OFF (every request faults its rows synchronously, two
    stalls per request) then ON (the prefetcher peeks the queue and
    promotes whole lookahead windows off-thread, one stall per window
    per table) — and the artifact records both QPS-at-p99 numbers, the
    measured speedup, the tier hit-rate / prefetch-coverage /
    promotion-latency gauges per arm, and whether the f32 served
    outputs were exactly equal between the arms (they must be: the
    tiers move rows, never values)."""
    import shutil
    import tempfile

    import numpy as np

    from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
    from dlrover_tpu.serving.sparse_engine import (
        SparseServingServer,
        merged_tier_snapshot,
        tier_model_tables,
    )
    from dlrover_tpu.sparse import GroupAdam
    from dlrover_tpu.sparse.tiered import TierStats

    far_future = 2**60  # demote-everything cutoff
    cfg = DeepFMConfig(
        n_fields=n_fields, n_dense=n_dense, emb_dim=emb_dim,
        mlp_dims=(32,), seed=seed,
    )
    rng = np.random.default_rng(seed)
    cat = rng.integers(
        0, id_space, size=(n_requests, n_fields)
    ).astype(np.int64)
    dense = rng.normal(size=(n_requests, n_dense)).astype(np.float32)
    labels = (rng.random(n_requests) < 0.3).astype(np.float32)

    model = DeepFM(cfg, optimizer=GroupAdam(lr=5e-3), dense_lr=5e-3)
    tmp = tempfile.mkdtemp(prefix="sparse_serve_bench_")
    try:
        tiered = tier_model_tables(model, tmp)
        for _ in range(2):  # create + train every row the trace touches
            model.train_step(cat, dense, labels)
        demoted = sum(
            t.demote_before_timestamp(far_future) for t in tiered
        )
        for t in tiered:  # calibrate the cold tier AFTER seeding it
            t.cold = _CalibratedColdStore(
                t.cold, cold_get_latency_ms / 1e3
            )

        def one_pass(prefetch):
            srv = SparseServingServer(
                model, cfg, replica="sparse-bench", prefetch=prefetch,
                prefetch_lookahead=prefetch_lookahead,
                max_queue=max(1024, 2 * n_requests), max_batch=1,
            ).start()
            try:
                # warmup: first tracing of the eager forward path
                srv.predict(cat[0], dense[0], timeout=600.0)
                # restore the fully-cold profile and zero the gauges so
                # both arms start from the identical tier state
                for t in tiered:
                    t.demote_before_timestamp(far_future)
                    t.stats = TierStats()
                srv.scheduler.reset_latencies()
                srv.engine._completed = 0
                srv.engine._t0 = 0.0
                t0 = time.perf_counter()
                futs = [
                    srv.submit(cat[i], dense[i]).future
                    for i in range(n_requests)
                ]
                scores = np.array(
                    [f.result(timeout=600.0)[0] for f in futs],
                    np.float32,
                )
                dt = time.perf_counter() - t0
                lat = srv.scheduler.latency_summary()
                tiers = merged_tier_snapshot(tiered)
            finally:
                srv.stop()
            qps = n_requests / dt if dt > 0 else 0.0
            return qps, dt, lat, tiers, scores

        qps_off, dt_off, lat_off, tiers_off, scores_off = one_pass(False)
        qps_on, dt_on, lat_on, tiers_on, scores_on = one_pass(True)
    finally:
        try:
            model.close()
        except Exception:  # noqa: BLE001
            pass
        shutil.rmtree(tmp, ignore_errors=True)

    def _tier_block(t):
        return {
            "hot_hit_rate": round(float(t["hot_hit_rate"]), 4),
            "prefetch_coverage": round(
                float(t["prefetch_coverage"]), 4
            ),
            "promote_latency_avg_ms": round(
                float(t["promote_latency_avg_ms"]), 3
            ),
            "cold_faults": int(t["cold_faults"]),
            "prefetched": int(t["prefetched"]),
            "hot_rows": int(t["hot_rows"]),
            "cold_rows": int(t["cold_rows"]),
        }

    return {
        "metric": (
            f"sparse_serve_qps[deepfm{n_fields}x{emb_dim},f32,"
            f"cold{cold_get_latency_ms:g}ms]"
        ),
        "value": round(qps_on, 2),
        "unit": "requests_per_sec",
        "sparse_qps": round(qps_on, 2),
        "sparse_qps_prefetch_off": round(qps_off, 2),
        "sparse_prefetch_speedup": (
            round(qps_on / qps_off, 3) if qps_off > 0 else None
        ),
        "sparse_p99_ms": round(lat_on["p99"], 2),
        "sparse_p99_ms_prefetch_off": round(lat_off["p99"], 2),
        "sparse_p99_target_ms": p99_target_ms,
        "sparse_p99_met": lat_on["p99"] <= p99_target_ms,
        "sparse_queue_wait_p99_ms": round(
            lat_on["queue_wait_p99_ms"], 2
        ),
        # the correctness half of the comparison: prefetch moves rows
        # across tiers, never values — the served scores must match
        # bitwise between the arms at the same seed
        "sparse_outputs_exact_equal": bool(
            np.array_equal(scores_on, scores_off)
        ),
        "cold_get_latency_ms": cold_get_latency_ms,
        "n_requests": n_requests,
        "demoted_rows": int(demoted),
        "wall_s": {
            "prefetch_on": round(dt_on, 4),
            "prefetch_off": round(dt_off, 4),
        },
        "tiers": {
            "prefetch_on": _tier_block(tiers_on),
            "prefetch_off": _tier_block(tiers_off),
        },
    }


def run_config(name, batch, seq, remat, steps=30, warmup=3,
               state_dtype="bfloat16", block_k=1):
    # steps=30: the axon relay's ~100ms host-readback latency is paid
    # once after the timed loop; at 10 steps it shaved ~3% off measured
    # MFU, at 30 it is under 1%.
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models import get_config
    from dlrover_tpu.parallel.mesh import single_device_mesh
    from dlrover_tpu.train import (
        TrainStepBuilder,
        init_train_state,
        make_optimizer,
    )

    cfg = get_config(
        name, max_seq=seq, remat=remat, param_dtype="bfloat16"
    )
    mesh = single_device_mesh()
    opt = make_optimizer(
        learning_rate=1e-4,
        warmup_steps=10,
        decay_steps=1000,
        state_dtype=state_dtype,
    )
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    builder = TrainStepBuilder(cfg, mesh, opt)

    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0, 1000)
    batch_data = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    if block_k > 1:
        # fused K-step mode: one dispatch covers block_k steps over a
        # [K, ...]-stacked batch; whole blocks only, so the per-step
        # numbers divide evenly
        step = builder.build_block()
        batch_data = jax.tree.map(
            lambda x: jnp.stack([x] * block_k), batch_data
        )
        n_dispatch = max(steps // block_k, 1)
        n_warm = max(warmup // block_k, 1)
    else:
        step = builder.build()
        n_dispatch = steps
        n_warm = warmup
    total_steps = n_dispatch * block_k

    # AOT-compile so the OPTIMIZED HLO (post-layout, post-fusion — the
    # module the scheduler actually runs) is in hand for the collective
    # profile; the compiled executable then serves as the step, so the
    # timed loop measures exactly the module that was profiled. Falls
    # back to plain jit if the AOT path is unavailable (relay backends
    # without serializable executables).
    hlo_text = ""
    try:
        compiled = step.lower(state, batch_data).compile()
        hlo_text = compiled.as_text()
        step = compiled
    except Exception:  # noqa: BLE001
        pass

    # sync via HOST READBACK, not block_until_ready: under the axon TPU
    # relay block_until_ready returns before device completion, which
    # would inflate throughput ~1000x; float() must wait for the value
    for _ in range(n_warm):
        state, metrics = step(state, batch_data)
    warm_loss = float(jnp.ravel(metrics["loss"])[-1])

    # host dispatch time = what the fused loop amortizes: the Python/
    # jit-call overhead per enqueue, measured call-entry to call-return
    # (the device keeps computing after the call returns)
    dispatch_s = 0.0
    t0 = time.perf_counter()
    for _ in range(n_dispatch):
        td = time.perf_counter()
        state, metrics = step(state, batch_data)
        dispatch_s += time.perf_counter() - td
    final_loss = float(jnp.ravel(metrics["loss"])[-1])
    dt = time.perf_counter() - t0
    if not math.isfinite(final_loss):
        raise RuntimeError(
            f"non-finite loss {final_loss} (warmup {warm_loss}): "
            "bench run is numerically invalid"
        )

    tokens_per_s = total_steps * batch * seq / dt
    model_tflops = cfg.flops_per_token(seq) * tokens_per_s / 1e12
    dev = jax.devices()[0]
    mfu = model_tflops / peak_tflops(dev)
    tag = f",k{block_k}" if block_k > 1 else ""
    overlap = None
    stats = None
    if hlo_text:
        stats = collective_stats(hlo_text)
        if stats["counts"]:
            # per-STEP collective budget: the block HLO carries K steps
            overlap = overlap_report(
                {
                    "bytes_by_op": {
                        op: b / block_k
                        for op, b in stats["bytes_by_op"].items()
                    }
                },
                dt / total_steps * 1e6,
                device_kind=getattr(dev, "device_kind", ""),
            )
    if overlap is not None:
        # compile-time planning numbers become runtime telemetry gauges
        # (plan_* / overlap_* in the metric collectors) so the tuner and
        # brain can compare plan vs measurement without re-running bench
        from dlrover_tpu.observability import telemetry

        hub = telemetry.get_hub()
        if hub.enabled:
            hub.publish(
                telemetry.plan_record_from_overlap(
                    f"{cfg.name},b{batch}x{seq}{tag}",
                    overlap,
                    suggest_bucket_mb(
                        cfg.num_params() * 4,
                        device_kind=getattr(dev, "device_kind", ""),
                    ),
                    getattr(builder, "update_sharding_reason", ""),
                    planned_step_time_s=dt / total_steps,
                )
            )

    # sentinel cost at this shape: a short back-to-back pair (sentinels
    # on vs the already-compiled off step) — the <1% acceptance number
    # the docs' cost model quotes. None when the probe fails or is
    # disabled (the probe pays a second step compile, which smoke tests
    # on tiny hosts opt out of via DLROVER_TPU_SENTINEL_PROBE=0).
    sentinel_overhead_frac = None
    try:
        if os.environ.get("DLROVER_TPU_SENTINEL_PROBE", "1") == "0":
            raise RuntimeError("probe disabled")
        sb = TrainStepBuilder(cfg, mesh, opt, health_sentinels=True)
        s_step = sb.build_block() if block_k > 1 else sb.build()
        s_state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        n_probe = max(min(n_dispatch, 10), 3)
        for _ in range(2):
            s_state, s_metrics = s_step(s_state, batch_data)
        float(jnp.ravel(s_metrics["loss"])[-1])  # sync (relay-safe)
        ts = time.perf_counter()
        for _ in range(n_probe):
            s_state, s_metrics = s_step(s_state, batch_data)
        float(jnp.ravel(s_metrics["loss"])[-1])
        t_on = time.perf_counter() - ts
        ts = time.perf_counter()
        for _ in range(n_probe):
            state, metrics = step(state, batch_data)
        float(jnp.ravel(metrics["loss"])[-1])
        t_off = time.perf_counter() - ts
        if t_off > 0:
            sentinel_overhead_frac = round(t_on / t_off - 1.0, 4)
    except Exception:  # noqa: BLE001
        pass
    return {
        "metric": (
            f"train_mfu[{cfg.name},b{batch}x{seq}{tag},{dev.device_kind}]"
        ),
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / _REFERENCE_HFU, 4),
        "tokens_per_sec": round(tokens_per_s, 1),
        "model_tflops_per_sec": round(model_tflops, 2),
        "flop_expansion_est": _FLOP_EXPANSION.get(remat, 1.0),
        "block_k": block_k,
        "host_dispatch_us_per_step": round(
            dispatch_s / total_steps * 1e6, 1
        ),
        "sentinel_overhead_frac": sentinel_overhead_frac,
        "collectives": stats,
        "overlap": overlap,
        # the elastic half of the trajectory: how long the last drilled
        # failure stopped training (None until a drill has run)
        "elastic_recovery": drill_recovery_metric(),
        # the serving half: tokens/s at fixed p99 from the last
        # `bench.py serve` artifact (None until serving has been benched)
        "serving": serving_trajectory_metric(),
        # the recommender half: QPS at fixed p99 with tiered hit-rates
        # from the last `bench.py sparse_serve` artifact (None until the
        # sparse arm has been benched; old SERVE artifacts are untouched)
        "sparse_serving": sparse_serving_trajectory_metric(),
        # the brain's cold-start plan for this shape vs the hand-tuned
        # row above, plus the live-refinement reaction time (in-process
        # drill; see tuned_arm_metric)
        "tuned": tuned_arm_metric(
            name, batch, seq, remat,
            device_kind=getattr(dev, "device_kind", ""),
        ),
    }


# Executed/counted FLOP ratio by remat tier (fwd+bwd counted as 3×fwd;
# backward re-runs the non-pinned share of the forward): remat recompute
# is real MXU work that MFU deliberately does not credit. Estimates from
# the measured step anatomy (README "Performance notes").
_FLOP_EXPANSION = {
    "full": round((3 + 1.0) / 3, 3),
    "dots_saveable": round((3 + 0.35) / 3, 3),
    "save_attn": round((3 + 0.9) / 3, 3),
    "save_qkv": round((3 + 0.7) / 3, 3),
    # same residual set as save_qkv — the recompute share is identical;
    # the host DMA cost shows up as step time, not as counted flops
    "save_qkv_offload": round((3 + 0.7) / 3, 3),
    "save_qkv_gate": round((3 + 0.5) / 3, 3),
    "save_dots": round((3 + 0.3) / 3, 3),
    "offload_attn": round((3 + 0.9) / 3, 3),
    "none": 1.0,
}


def _classify_failure(returncode, stderr_text: str) -> str:
    """Bucket a failed attempt for the per-attempt JSON line: the
    BENCH_*.json consumer needs to tell a too-small budget (timeout)
    from a config that no longer fits (oom) from a code regression
    (compile_error / error) without digging through driver stderr."""
    txt = stderr_text or ""
    low = txt.lower()
    if any(
        pat in txt
        for pat in ("RESOURCE_EXHAUSTED", "ResourceExhausted")
    ) or "out of memory" in low or "allocation failure" in low:
        return "oom"
    if any(
        pat in txt
        for pat in (
            "Compilation failure",
            "XlaCompile",
            "Mosaic",
            "INVALID_ARGUMENT",
        )
    ) or "lowering" in low or "compilation" in low:
        return "compile_error"
    if returncode is None:
        return "timeout"
    return "error"


def _nonmatmul_us_per_step(record, name, batch, seq, remat):
    """Non-matmul residue per step, from the matmuls-only
    counterfactual: if every EXECUTED flop (counted × remat expansion)
    ran at the measured chained-matmul rate for this shape set, the
    step would take executed/rate seconds — the remainder is
    elementwise/HBM time the MXU never sees (norms, residual adds,
    rope, optimizer). Estimate only: attention flops run through the
    flash kernel, not the matmul chain, so at long seq this reads as a
    LOWER bound (clamped at 0). None when the ceiling wasn't measured
    (CPU smoke runs)."""
    ceiling_key = (
        "mxu_ceiling_frac_gpt2_shapes"
        if name.startswith("gpt2")
        else "mxu_ceiling_frac"
    )
    if not (
        record.get(ceiling_key)
        and record.get("mxu_ceiling_frac")
        and record.get("mxu_tflops")
        and record.get("tokens_per_sec")
    ):
        return None
    step_us = batch * seq / record["tokens_per_sec"] * 1e6
    peak_rate = record["mxu_tflops"] / record["mxu_ceiling_frac"]
    shape_rate = peak_rate * record[ceiling_key]
    executed = record["model_tflops_per_sec"] * _FLOP_EXPANSION.get(
        remat, 1.0
    )
    return round(max(0.0, step_us * (1 - executed / shape_rate)), 1)


def main():
    if len(sys.argv) >= 2 and sys.argv[1] == "--check":
        print(json.dumps({"kernels_ok": check_kernels()}))
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--ceiling":
        print(json.dumps(measure_mxu_ceiling()))
        return
    if len(sys.argv) >= 2 and sys.argv[1] in ("serve", "--serve"):
        mode = sys.argv[2] if len(sys.argv) > 2 else "int8"
        n_requests = int(sys.argv[3]) if len(sys.argv) > 3 else 8
        max_len = int(sys.argv[4]) if len(sys.argv) > 4 else 64
        record = run_serve(
            mode=mode, n_requests=n_requests, max_len=max_len
        )
        out = os.environ.get("DLROVER_TPU_SERVE_ARTIFACT_OUT")
        if out:
            with open(out, "w") as f:
                json.dump(record, f)
        print(json.dumps(record))
        return
    if len(sys.argv) >= 2 and sys.argv[1] in (
        "sparse_serve", "--sparse-serve"
    ):
        n_requests = int(sys.argv[2]) if len(sys.argv) > 2 else 160
        cold_ms = float(sys.argv[3]) if len(sys.argv) > 3 else 8.0
        record = run_sparse_serve(
            n_requests=n_requests, cold_get_latency_ms=cold_ms
        )
        out = os.environ.get("DLROVER_TPU_SPARSE_SERVE_ARTIFACT_OUT")
        if out:
            with open(out, "w") as f:
                json.dump(record, f)
        print(json.dumps(record))
        return
    if len(sys.argv) >= 5 and sys.argv[1] == "--single":
        name, batch, seq, remat = (
            sys.argv[2],
            int(sys.argv[3]),
            int(sys.argv[4]),
            sys.argv[5] if len(sys.argv) > 5 else "none",
        )
        state_dtype = sys.argv[6] if len(sys.argv) > 6 else "bfloat16"
        block_k = int(sys.argv[7]) if len(sys.argv) > 7 else 1
        print(
            json.dumps(
                run_config(
                    name, batch, seq, remat,
                    state_dtype=state_dtype, block_k=block_k,
                )
            )
        )
        return

    t0 = time.monotonic()
    failed_attempts = []
    for name, batch, seq, remat, budget_s in _ATTEMPTS:
        attempt_id = f"{name},b{batch}x{seq},{remat}"
        try:
            out = subprocess.run(
                [
                    sys.executable,
                    os.path.abspath(__file__),
                    "--single",
                    name,
                    str(batch),
                    str(seq),
                    remat,
                ],
                capture_output=True,
                timeout=budget_s,
                text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                line = out.stdout.strip().splitlines()[-1]
                record = json.loads(line)  # validate
                # on-chip kernel numerics gate: runs ONCE, in its own
                # subprocess (a kernel hang cannot eat the bench), and
                # only inside whatever remains of the documented 900s
                # envelope — when attempts already consumed it, the
                # check reports null rather than risking the result
                # line itself
                remaining = _DEADLINE_S - (time.monotonic() - t0)
                if remaining >= 45:
                    record["kernels_ok"] = _run_kernel_check(
                        budget_s=int(min(180, remaining))
                    )
                else:
                    sys.stderr.write(
                        "kernel check skipped: bench budget exhausted\n"
                    )
                    record["kernels_ok"] = None
                # achievable-matmul ceiling at the flagship shapes:
                # contextualizes the MFU (remaining gap = remat
                # recompute vs this, not vs the nominal peak)
                remaining = _DEADLINE_S - (time.monotonic() - t0)
                if remaining >= 45:
                    record.update(
                        _run_aux_json(
                            "--ceiling", int(min(120, remaining))
                        )
                    )
                # how close the schedule runs to the ACHIEVABLE rate:
                # executed flops (counted × remat expansion) against the
                # measured chained-matmul ceiling AT THE WINNING
                # CONFIG'S shapes (gpt2 fallbacks pad d=1600 on the MXU
                # — judging them against the llama-shape ceiling would
                # understate them ~10-15%). ~1.0 means the remaining
                # vs_baseline gap is the remat recompute HBM forces,
                # not scheduling losses.
                ceiling_key = (
                    "mxu_ceiling_frac_gpt2_shapes"
                    if name.startswith("gpt2")
                    else "mxu_ceiling_frac"
                )
                nonmatmul = _nonmatmul_us_per_step(
                    record, name, batch, seq, remat
                )
                if nonmatmul is not None:
                    record["nonmatmul_us_per_step"] = nonmatmul
                # the interpretation only holds while trunk matmuls
                # dominate: at long seq the flash kernel's attention
                # flops (not represented in the matmul-chain ceiling,
                # and with a seq-dependent recompute share) push the
                # ratio past 1.0 — emit nothing rather than a
                # >100%-of-achievable number
                if seq > 4096:
                    record.pop("flop_expansion_est", None)
                elif record.get(ceiling_key):
                    record["schedule_vs_achievable"] = round(
                        record["value"]
                        * record.get("flop_expansion_est", 1.0)
                        / record[ceiling_key],
                        3,
                    )
                # seq-matched companion: when the long-context config
                # wins, also measure at the baseline's own seq (4096)
                # so the record carries the apples-to-apples number
                if seq > _BASELINE_SEQ_COMPANION[2]:
                    remaining = _DEADLINE_S - (time.monotonic() - t0)
                    if remaining >= 120:
                        cn, cb, cs, cr = _BASELINE_SEQ_COMPANION
                        comp = _run_aux_json(
                            [
                                "--single", cn, str(cb), str(cs), cr
                            ],
                            int(min(220, remaining)),
                        )
                        if comp.get("value"):
                            record["mfu_at_baseline_seq4096"] = comp[
                                "value"
                            ]
                            record["vs_baseline_at_seq4096"] = comp[
                                "vs_baseline"
                            ]
                # keep the gpt2 series measured when the llama family
                # wins: one fallback-family run rides along so both
                # shape families carry numbers every round
                if not name.startswith("gpt2") and name != "tiny":
                    remaining = _DEADLINE_S - (time.monotonic() - t0)
                    if remaining >= 130:
                        fn, fb_b, fb_s, fb_r = _GPT2_FALLBACK
                        fb = _run_aux_json(
                            [
                                "--single", fn, str(fb_b), str(fb_s),
                                fb_r,
                            ],
                            int(min(220, remaining)),
                        )
                        if fb.get("value"):
                            record["fallback"] = {
                                "metric": fb["metric"],
                                "value": fb["value"],
                                "vs_baseline": fb["vs_baseline"],
                                "mxu_ceiling_frac": record.get(
                                    "mxu_ceiling_frac_gpt2_shapes"
                                ),
                            }
                    else:
                        sys.stderr.write(
                            "gpt2 fallback skipped: budget exhausted\n"
                        )
                if failed_attempts:
                    # larger configs that died before this one won:
                    # carried in the winning record so BENCH_*.json
                    # alone shows WHY the bench fell through
                    record["failed_attempts"] = failed_attempts
                print(json.dumps(record))
                return
            fail = {
                "attempt": attempt_id,
                "failure": _classify_failure(
                    out.returncode, out.stderr
                ),
            }
            failed_attempts.append(fail)
            print(json.dumps(fail))
            sys.stderr.write(
                f"bench config {name} rc={out.returncode}: "
                f"{out.stderr[-800:]}\n"
            )
        except subprocess.TimeoutExpired as e:
            stderr = e.stderr
            if isinstance(stderr, bytes):
                stderr = stderr.decode("utf-8", "replace")
            fail = {
                "attempt": attempt_id,
                "failure": _classify_failure(None, stderr),
            }
            failed_attempts.append(fail)
            print(json.dumps(fail))
            sys.stderr.write(f"bench config {name} timed out ({budget_s}s)\n")
    raise SystemExit("all bench configs failed")


def _run_aux_json(flag, budget_s: int) -> dict:
    """Run ``bench.py <flag...>`` in a subprocess, parse its JSON line."""
    args = [flag] if isinstance(flag, str) else list(flag)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *args],
            capture_output=True,
            timeout=budget_s,
            text=True,
        )
        if out.returncode == 0 and out.stdout.strip():
            return json.loads(out.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, json.JSONDecodeError):
        pass
    return {}


def _run_kernel_check(budget_s: int = 180):
    return _run_aux_json("--check", budget_s).get("kernels_ok", False)


if __name__ == "__main__":
    main()
