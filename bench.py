"""Single-chip training throughput benchmark.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: model FLOPs utilization (MFU) of a jitted train step on the largest
config that fits the local chip (lead attempt: llama-1.4b, whose dims all
tile the MXU exactly; gpt2-family fallbacks follow). The reference's
headline is Llama2-7B FSDP at 65.6% HFU on A100s (BASELINE.md #8);
``vs_baseline`` is our MFU / 0.656 — a hardware-neutral comparison of how
well each framework drives its accelerator.

Each candidate config runs in a subprocess with its own timeout, so a hung
compile or OOM on the big config cannot eat the whole bench budget.
"""

import json
import math
import os
import subprocess
import sys
import time

# bf16 peak TFLOP/s per chip by device kind
_PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
    "cpu": 0.1,  # placeholder so the bench still runs off-TPU
}

_REFERENCE_HFU = 0.656  # BASELINE.md #8

# one deadline for the whole run: attempts + aux passes must fit the
# documented `timeout 900 python bench.py` with slack for interpreter
# startup (the per-attempt budgets below must sum to <= this)
_DEADLINE_S = 870

# (config, batch, seq, remat, subprocess timeout seconds)
# llama-1.4b leads: every hot dim is a 128-multiple (d=16·128,
# head_dim=128, ff=44·128), measured ~10 MFU points over gpt2-1.5b's
# d=1600/head_dim=64 shapes on v5e — the MXU tiles cleanly.
# remat=save_qkv: fused CE (ops/fused_ce.py) freed the ~2 GiB f32
# logits working set, which buys pinning the qkv projections + flash
# residuals — backward skips ~30% of the full-remat recompute flops.
# Sequence length: b1·s8192 leads (same 8192 tokens/step as b8·s1024,
# so identical optimizer amortization and activation footprint) —
# longer sequences spend MORE of each token's flops in attention, which
# the Pallas flash kernel runs at MXU density, so utilization RISES
# with context length (measured r3, save_qkv: 0.626 b8·s1024 → 0.651
# b2·s4096 → 0.692 b1·s8192; 0.667 b1·s16384 save_attn). The
# reference's 65.6% HFU headline ran BLOCK_SIZE=4096
# (fsdp_llama2_entry.sh:11); the s4096 attempt is the seq-matched
# comparison and rides along as mfu_at_baseline_seq4096 in the
# emitted record.
# budgets sum to ≤870s so the documented `timeout 900 python bench.py`
# always reaches the tiny config even if every larger attempt grinds to
# its per-attempt timeout (CPU fall-through worst case)
_ATTEMPTS = [
    ("llama-1.4b", 1, 8192, "save_qkv", 280),
    ("llama-1.4b", 2, 4096, "save_qkv", 170),
    ("llama-1.4b", 8, 1024, "save_qkv", 110),
    # gpt2-1.5b's tied 50k-vocab embedding puts params at 1.56B, so
    # save_qkv's HBM-pinned residuals OOM the 16 GiB chip — but the
    # offload twin keeps the same residual set in pinned host memory,
    # escaping full remat's ~30% backward recompute; with d=64 the
    # attention kernels also run head-packed (attn_head_pack auto)
    ("gpt2-1.5b", 8, 1024, "save_qkv_offload", 110),
    ("gpt2-355m", 16, 1024, "full", 60),
    ("gpt2-124m", 16, 512, "none", 60),
    ("tiny", 8, 128, "none", 80),
]

# seq-matched companion for the long-context lead config (the baseline
# measured at 4096): embedded in the record when budget allows. Derived
# from the attempt ladder so the fallback record and the companion are
# always the SAME recipe.
_BASELINE_SEQ_COMPANION = _ATTEMPTS[1][:4]
assert _BASELINE_SEQ_COMPANION[2] == 4096

# the gpt2-family fallback stays MEASURED even when the flagship wins
# (BASELINE.md #8 is judged per shape family; without this the gpt2
# series would only appear in rounds where the flagship fails) —
# embedded as record["fallback"] when budget allows
_GPT2_FALLBACK = _ATTEMPTS[3][:4]
assert _GPT2_FALLBACK[0].startswith("gpt2")


# (n_head, head_dim) pairs the flash gate runs: the flagship's clean
# 128-wide heads AND the gpt2-1.5b narrow-head shape, whose odd 25
# heads exercise auto head-packing (pack=2) plus the zero-pad path
_KERNEL_CHECK_SHAPES = [(16, 128), (25, 64)]


def check_kernels(b=2, s=1024) -> bool:
    """On-chip numerics gate for BOTH hand-written gradients in the hot
    path: the Pallas flash kernels (fwd+bwd vs mha_reference, at every
    _KERNEL_CHECK_SHAPES head geometry) and the fused lm-head
    cross-entropy custom_vjp (vs the materialized-logits path).

    Runs at bench-like shapes on the REAL device (tests/test_ops.py and
    tests/test_fused_ce.py cover CPU/interpret mode only), so silent
    tile/clamp/chunk regressions show up in the BENCH json as
    kernels_ok=false instead of as quietly-wrong training.
    """
    import jax
    import numpy as np

    if jax.default_backend() == "cpu":
        return True  # the CPU fall-through path has no kernel to check

    def close(a, b, tol):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        denom = np.maximum(np.abs(b).max(), 1e-6)
        return float(np.abs(a - b).max() / denom) < tol

    ok = True
    for h, d in _KERNEL_CHECK_SHAPES:
        ok = ok and _check_flash_shape(close, b, s, h, d)
    return bool(ok) and _check_fused_ce(close)


def _check_flash_shape(close, b, s, h, d) -> bool:
    """Flash fwd+bwd vs mha_reference at one head geometry."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.ops.attention import mha_reference
    from dlrover_tpu.ops.pallas_attention import flash_attention

    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=True, block_q=1024,
                              block_k=1024)
        return jnp.sum(out.astype(jnp.float32) ** 2), out

    def loss_ref(q, k, v):
        out = mha_reference(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2), out

    (lf, of), gf = jax.jit(
        jax.value_and_grad(loss_flash, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    (lr_, orr), gr = jax.jit(
        jax.value_and_grad(loss_ref, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)

    ok = close(of, orr, 2e-2)
    for a, b_ in zip(gf, gr):
        ok = ok and close(a, b_, 3e-2)
    return bool(ok)


def _check_fused_ce(close, b=2, s=512, dm=2048, v=32000) -> bool:
    """Fused CE vs materialized logits: logz + grads w.r.t. x and w."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.ops.fused_ce import fused_linear_ce

    kx, kw, kt = jax.random.split(jax.random.key(11), 3)
    x = jax.random.normal(kx, (b, s, dm), jnp.bfloat16)
    w = jax.random.normal(kw, (dm, v), jnp.bfloat16) * 0.02
    t = jax.random.randint(kt, (b, s), 0, v)

    def nll_fused(x, w):
        logz, tgt, _ = fused_linear_ce(x, w, t)
        return jnp.mean(logz - tgt)

    def nll_ref(x, w):
        logits = jnp.einsum(
            "bsd,dv->bsv", x, w, preferred_element_type=jnp.float32
        )
        logz = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t[..., None], -1)[..., 0]
        return jnp.mean(logz - tgt)

    lf, gf = jax.jit(jax.value_and_grad(nll_fused, argnums=(0, 1)))(x, w)
    lr, gr = jax.jit(jax.value_and_grad(nll_ref, argnums=(0, 1)))(x, w)
    ok = abs(float(lf) - float(lr)) / max(abs(float(lr)), 1e-6) < 1e-2
    for a, b_ in zip(gf, gr):
        ok = ok and close(a, b_, 3e-2)
    return bool(ok)


def measure_mxu_ceiling(n_pairs: int = 40, reps: int = 5) -> dict:
    """Achievable chained-matmul rate at the flagship's MLP shapes, plus
    the gpt2-1.5b fallback's shapes for comparison.

    The practical ceiling the step competes against — NOT the nominal
    peak. The second measurement quantifies the fallback config's
    documented shape penalty (d=1600 is 12.5 MXU tiles, so every matmul
    pads 1600 -> 1664): the bound the gpt2-1.5b MFU should be judged
    against rides in the BENCH json instead of only in the README.
    Methodology matters under the axon relay: a single timed call folds
    the ~100 ms host-readback into the measurement and reads 40-70%
    low; chaining ``reps`` calls and syncing once amortizes it.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    if jax.default_backend() == "cpu":
        # ~151 TFLOP of chained matmuls would grind past the subprocess
        # timeout on the CPU fall-through path, and the ratio against
        # the 0.1-TFLOPS placeholder peak is meaningless anyway
        return {}
    dev = jax.devices()[0]

    def chained_rate(n, d, f):
        a0 = jax.random.normal(jax.random.key(5), (n, d), jnp.bfloat16)
        wm = jax.random.normal(jax.random.key(6), (d, f), jnp.bfloat16)
        wm = wm * 0.02
        wn = jax.random.normal(jax.random.key(7), (f, d), jnp.bfloat16)
        wn = wn * 0.0005

        @jax.jit
        def chain(a):
            def body(c, _):
                c = jnp.dot(c, wm, preferred_element_type=jnp.bfloat16)
                c = jnp.dot(c, wn, preferred_element_type=jnp.bfloat16)
                return c, None

            out, _ = jax.lax.scan(body, a, None, length=n_pairs)
            return out

        out = chain(a0)
        float(jnp.sum(out.astype(jnp.float32)))  # warm + sync
        t0 = _time.perf_counter()
        for _ in range(reps):
            out = chain(out)
        float(jnp.sum(out.astype(jnp.float32)))
        dt = _time.perf_counter() - t0
        fl = 2 * n * d * f * 2 * n_pairs * reps
        return fl / dt / 1e12

    tf = chained_rate(8192, 2048, 5632)  # llama-1.4b MLP shapes
    tf_gpt2 = chained_rate(8192, 1600, 6400)  # gpt2-1.5b MLP shapes
    return {
        "mxu_tflops": round(tf, 1),
        "mxu_ceiling_frac": round(tf / peak_tflops(dev), 4),
        "mxu_ceiling_frac_gpt2_shapes": round(
            tf_gpt2 / peak_tflops(dev), 4
        ),
    }


def peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in _PEAK_TFLOPS.items():
        if key in kind:
            return val
    return 197.0


# bytes per element for the HLO shape dtypes that ride collectives
_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVE_OPS = (
    "reduce-scatter",
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
)


def collective_stats(hlo_text: str) -> dict:
    """Per-step collective profile of an optimized HLO module.

    Returns ``{"counts": {op: n}, "bytes_by_dtype": {dtype: B}}`` —
    op counts for each collective kind and the summed RESULT payload
    bytes grouped by wire dtype. This is what the MULTICHIP dryrun
    embeds in its record so a replicated-update regression (full-
    gradient all-reduce sneaking back in) or a wire-dtype change is
    visible in the trajectory, not just in local tests.
    """
    import re

    shape_re = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    counts = {op: 0 for op in _COLLECTIVE_OPS}
    bytes_by_dtype: dict = {}
    for line in hlo_text.splitlines():
        parts = line.split(" = ", 1)
        if len(parts) != 2:
            continue
        rhs = parts[1]
        hit = None
        for op in _COLLECTIVE_OPS:
            k = rhs.find(op + "(")
            if k >= 0:
                hit = (op, k)
                break
        if hit is None:
            continue
        op, k = hit
        counts[op] += 1
        for dt, dims in shape_re.findall(rhs[:k]):
            if dt not in _HLO_DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            bytes_by_dtype[dt] = (
                bytes_by_dtype.get(dt, 0) + n * _HLO_DTYPE_BYTES[dt]
            )
    return {
        "counts": {k: v for k, v in counts.items() if v},
        "bytes_by_dtype": bytes_by_dtype,
    }


def run_config(name, batch, seq, remat, steps=30, warmup=3,
               state_dtype="bfloat16", block_k=1):
    # steps=30: the axon relay's ~100ms host-readback latency is paid
    # once after the timed loop; at 10 steps it shaved ~3% off measured
    # MFU, at 30 it is under 1%.
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models import get_config
    from dlrover_tpu.parallel.mesh import single_device_mesh
    from dlrover_tpu.train import (
        TrainStepBuilder,
        init_train_state,
        make_optimizer,
    )

    cfg = get_config(
        name, max_seq=seq, remat=remat, param_dtype="bfloat16"
    )
    mesh = single_device_mesh()
    opt = make_optimizer(
        learning_rate=1e-4,
        warmup_steps=10,
        decay_steps=1000,
        state_dtype=state_dtype,
    )
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    builder = TrainStepBuilder(cfg, mesh, opt)

    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0, 1000)
    batch_data = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    if block_k > 1:
        # fused K-step mode: one dispatch covers block_k steps over a
        # [K, ...]-stacked batch; whole blocks only, so the per-step
        # numbers divide evenly
        step = builder.build_block()
        batch_data = jax.tree.map(
            lambda x: jnp.stack([x] * block_k), batch_data
        )
        n_dispatch = max(steps // block_k, 1)
        n_warm = max(warmup // block_k, 1)
    else:
        step = builder.build()
        n_dispatch = steps
        n_warm = warmup
    total_steps = n_dispatch * block_k

    # sync via HOST READBACK, not block_until_ready: under the axon TPU
    # relay block_until_ready returns before device completion, which
    # would inflate throughput ~1000x; float() must wait for the value
    for _ in range(n_warm):
        state, metrics = step(state, batch_data)
    warm_loss = float(jnp.ravel(metrics["loss"])[-1])

    # host dispatch time = what the fused loop amortizes: the Python/
    # jit-call overhead per enqueue, measured call-entry to call-return
    # (the device keeps computing after the call returns)
    dispatch_s = 0.0
    t0 = time.perf_counter()
    for _ in range(n_dispatch):
        td = time.perf_counter()
        state, metrics = step(state, batch_data)
        dispatch_s += time.perf_counter() - td
    final_loss = float(jnp.ravel(metrics["loss"])[-1])
    dt = time.perf_counter() - t0
    if not math.isfinite(final_loss):
        raise RuntimeError(
            f"non-finite loss {final_loss} (warmup {warm_loss}): "
            "bench run is numerically invalid"
        )

    tokens_per_s = total_steps * batch * seq / dt
    model_tflops = cfg.flops_per_token(seq) * tokens_per_s / 1e12
    dev = jax.devices()[0]
    mfu = model_tflops / peak_tflops(dev)
    tag = f",k{block_k}" if block_k > 1 else ""
    return {
        "metric": (
            f"train_mfu[{cfg.name},b{batch}x{seq}{tag},{dev.device_kind}]"
        ),
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / _REFERENCE_HFU, 4),
        "tokens_per_sec": round(tokens_per_s, 1),
        "model_tflops_per_sec": round(model_tflops, 2),
        "flop_expansion_est": _FLOP_EXPANSION.get(remat, 1.0),
        "block_k": block_k,
        "host_dispatch_us_per_step": round(
            dispatch_s / total_steps * 1e6, 1
        ),
    }


# Executed/counted FLOP ratio by remat tier (fwd+bwd counted as 3×fwd;
# backward re-runs the non-pinned share of the forward): remat recompute
# is real MXU work that MFU deliberately does not credit. Estimates from
# the measured step anatomy (README "Performance notes").
_FLOP_EXPANSION = {
    "full": round((3 + 1.0) / 3, 3),
    "dots_saveable": round((3 + 0.35) / 3, 3),
    "save_attn": round((3 + 0.9) / 3, 3),
    "save_qkv": round((3 + 0.7) / 3, 3),
    # same residual set as save_qkv — the recompute share is identical;
    # the host DMA cost shows up as step time, not as counted flops
    "save_qkv_offload": round((3 + 0.7) / 3, 3),
    "save_qkv_gate": round((3 + 0.5) / 3, 3),
    "save_dots": round((3 + 0.3) / 3, 3),
    "offload_attn": round((3 + 0.9) / 3, 3),
    "none": 1.0,
}


def _classify_failure(returncode, stderr_text: str) -> str:
    """Bucket a failed attempt for the per-attempt JSON line: the
    BENCH_*.json consumer needs to tell a too-small budget (timeout)
    from a config that no longer fits (oom) from a code regression
    (compile_error / error) without digging through driver stderr."""
    txt = stderr_text or ""
    low = txt.lower()
    if any(
        pat in txt
        for pat in ("RESOURCE_EXHAUSTED", "ResourceExhausted")
    ) or "out of memory" in low or "allocation failure" in low:
        return "oom"
    if any(
        pat in txt
        for pat in (
            "Compilation failure",
            "XlaCompile",
            "Mosaic",
            "INVALID_ARGUMENT",
        )
    ) or "lowering" in low or "compilation" in low:
        return "compile_error"
    if returncode is None:
        return "timeout"
    return "error"


def _nonmatmul_us_per_step(record, name, batch, seq, remat):
    """Non-matmul residue per step, from the matmuls-only
    counterfactual: if every EXECUTED flop (counted × remat expansion)
    ran at the measured chained-matmul rate for this shape set, the
    step would take executed/rate seconds — the remainder is
    elementwise/HBM time the MXU never sees (norms, residual adds,
    rope, optimizer). Estimate only: attention flops run through the
    flash kernel, not the matmul chain, so at long seq this reads as a
    LOWER bound (clamped at 0). None when the ceiling wasn't measured
    (CPU smoke runs)."""
    ceiling_key = (
        "mxu_ceiling_frac_gpt2_shapes"
        if name.startswith("gpt2")
        else "mxu_ceiling_frac"
    )
    if not (
        record.get(ceiling_key)
        and record.get("mxu_ceiling_frac")
        and record.get("mxu_tflops")
        and record.get("tokens_per_sec")
    ):
        return None
    step_us = batch * seq / record["tokens_per_sec"] * 1e6
    peak_rate = record["mxu_tflops"] / record["mxu_ceiling_frac"]
    shape_rate = peak_rate * record[ceiling_key]
    executed = record["model_tflops_per_sec"] * _FLOP_EXPANSION.get(
        remat, 1.0
    )
    return round(max(0.0, step_us * (1 - executed / shape_rate)), 1)


def main():
    if len(sys.argv) >= 2 and sys.argv[1] == "--check":
        print(json.dumps({"kernels_ok": check_kernels()}))
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--ceiling":
        print(json.dumps(measure_mxu_ceiling()))
        return
    if len(sys.argv) >= 5 and sys.argv[1] == "--single":
        name, batch, seq, remat = (
            sys.argv[2],
            int(sys.argv[3]),
            int(sys.argv[4]),
            sys.argv[5] if len(sys.argv) > 5 else "none",
        )
        state_dtype = sys.argv[6] if len(sys.argv) > 6 else "bfloat16"
        block_k = int(sys.argv[7]) if len(sys.argv) > 7 else 1
        print(
            json.dumps(
                run_config(
                    name, batch, seq, remat,
                    state_dtype=state_dtype, block_k=block_k,
                )
            )
        )
        return

    t0 = time.monotonic()
    failed_attempts = []
    for name, batch, seq, remat, budget_s in _ATTEMPTS:
        attempt_id = f"{name},b{batch}x{seq},{remat}"
        try:
            out = subprocess.run(
                [
                    sys.executable,
                    os.path.abspath(__file__),
                    "--single",
                    name,
                    str(batch),
                    str(seq),
                    remat,
                ],
                capture_output=True,
                timeout=budget_s,
                text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                line = out.stdout.strip().splitlines()[-1]
                record = json.loads(line)  # validate
                # on-chip kernel numerics gate: runs ONCE, in its own
                # subprocess (a kernel hang cannot eat the bench), and
                # only inside whatever remains of the documented 900s
                # envelope — when attempts already consumed it, the
                # check reports null rather than risking the result
                # line itself
                remaining = _DEADLINE_S - (time.monotonic() - t0)
                if remaining >= 45:
                    record["kernels_ok"] = _run_kernel_check(
                        budget_s=int(min(180, remaining))
                    )
                else:
                    sys.stderr.write(
                        "kernel check skipped: bench budget exhausted\n"
                    )
                    record["kernels_ok"] = None
                # achievable-matmul ceiling at the flagship shapes:
                # contextualizes the MFU (remaining gap = remat
                # recompute vs this, not vs the nominal peak)
                remaining = _DEADLINE_S - (time.monotonic() - t0)
                if remaining >= 45:
                    record.update(
                        _run_aux_json(
                            "--ceiling", int(min(120, remaining))
                        )
                    )
                # how close the schedule runs to the ACHIEVABLE rate:
                # executed flops (counted × remat expansion) against the
                # measured chained-matmul ceiling AT THE WINNING
                # CONFIG'S shapes (gpt2 fallbacks pad d=1600 on the MXU
                # — judging them against the llama-shape ceiling would
                # understate them ~10-15%). ~1.0 means the remaining
                # vs_baseline gap is the remat recompute HBM forces,
                # not scheduling losses.
                ceiling_key = (
                    "mxu_ceiling_frac_gpt2_shapes"
                    if name.startswith("gpt2")
                    else "mxu_ceiling_frac"
                )
                nonmatmul = _nonmatmul_us_per_step(
                    record, name, batch, seq, remat
                )
                if nonmatmul is not None:
                    record["nonmatmul_us_per_step"] = nonmatmul
                # the interpretation only holds while trunk matmuls
                # dominate: at long seq the flash kernel's attention
                # flops (not represented in the matmul-chain ceiling,
                # and with a seq-dependent recompute share) push the
                # ratio past 1.0 — emit nothing rather than a
                # >100%-of-achievable number
                if seq > 4096:
                    record.pop("flop_expansion_est", None)
                elif record.get(ceiling_key):
                    record["schedule_vs_achievable"] = round(
                        record["value"]
                        * record.get("flop_expansion_est", 1.0)
                        / record[ceiling_key],
                        3,
                    )
                # seq-matched companion: when the long-context config
                # wins, also measure at the baseline's own seq (4096)
                # so the record carries the apples-to-apples number
                if seq > _BASELINE_SEQ_COMPANION[2]:
                    remaining = _DEADLINE_S - (time.monotonic() - t0)
                    if remaining >= 120:
                        cn, cb, cs, cr = _BASELINE_SEQ_COMPANION
                        comp = _run_aux_json(
                            [
                                "--single", cn, str(cb), str(cs), cr
                            ],
                            int(min(220, remaining)),
                        )
                        if comp.get("value"):
                            record["mfu_at_baseline_seq4096"] = comp[
                                "value"
                            ]
                            record["vs_baseline_at_seq4096"] = comp[
                                "vs_baseline"
                            ]
                # keep the gpt2 series measured when the llama family
                # wins: one fallback-family run rides along so both
                # shape families carry numbers every round
                if not name.startswith("gpt2") and name != "tiny":
                    remaining = _DEADLINE_S - (time.monotonic() - t0)
                    if remaining >= 130:
                        fn, fb_b, fb_s, fb_r = _GPT2_FALLBACK
                        fb = _run_aux_json(
                            [
                                "--single", fn, str(fb_b), str(fb_s),
                                fb_r,
                            ],
                            int(min(220, remaining)),
                        )
                        if fb.get("value"):
                            record["fallback"] = {
                                "metric": fb["metric"],
                                "value": fb["value"],
                                "vs_baseline": fb["vs_baseline"],
                                "mxu_ceiling_frac": record.get(
                                    "mxu_ceiling_frac_gpt2_shapes"
                                ),
                            }
                    else:
                        sys.stderr.write(
                            "gpt2 fallback skipped: budget exhausted\n"
                        )
                if failed_attempts:
                    # larger configs that died before this one won:
                    # carried in the winning record so BENCH_*.json
                    # alone shows WHY the bench fell through
                    record["failed_attempts"] = failed_attempts
                print(json.dumps(record))
                return
            fail = {
                "attempt": attempt_id,
                "failure": _classify_failure(
                    out.returncode, out.stderr
                ),
            }
            failed_attempts.append(fail)
            print(json.dumps(fail))
            sys.stderr.write(
                f"bench config {name} rc={out.returncode}: "
                f"{out.stderr[-800:]}\n"
            )
        except subprocess.TimeoutExpired as e:
            stderr = e.stderr
            if isinstance(stderr, bytes):
                stderr = stderr.decode("utf-8", "replace")
            fail = {
                "attempt": attempt_id,
                "failure": _classify_failure(None, stderr),
            }
            failed_attempts.append(fail)
            print(json.dumps(fail))
            sys.stderr.write(f"bench config {name} timed out ({budget_s}s)\n")
    raise SystemExit("all bench configs failed")


def _run_aux_json(flag, budget_s: int) -> dict:
    """Run ``bench.py <flag...>`` in a subprocess, parse its JSON line."""
    args = [flag] if isinstance(flag, str) else list(flag)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *args],
            capture_output=True,
            timeout=budget_s,
            text=True,
        )
        if out.returncode == 0 and out.stdout.strip():
            return json.loads(out.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, json.JSONDecodeError):
        pass
    return {}


def _run_kernel_check(budget_s: int = 180):
    return _run_aux_json("--check", budget_s).get("kernels_ok", False)


if __name__ == "__main__":
    main()
